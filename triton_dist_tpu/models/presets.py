"""Named configs for the models the reference benchmarks.

The reference's published numbers are all Qwen3-8B / Qwen3-32B /
Qwen3-MoE decodes on TP8 (docs/getting-started/e2e/e2e_dense.md:21-38,
docs/mega_triton_kernel.md:30-39; Seed-OSS-36B README.md:82). These
presets reproduce those architectures so `AutoLLM.build(presets.*())` +
`parallel.plan_parallelism` give a reference user the same model menu
without hunting for HF config JSONs. Values follow the public HF
configs for the Qwen3 family.

The bench's `layer_8b`/`layer_32b` parts use the same dimensions
(hidden 4096/5120, inter 12288/25600, TP8 per-chip slices) — these
presets are the whole-model form of those shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

from triton_dist_tpu.models.config import ModelConfig


def qwen3_0_6b(**overrides) -> ModelConfig:
    """Qwen3-0.6B — the smallest real checkpoint; fits one chip easily.
    (Tied embeddings, like the HF config.)"""
    return _build(hidden_size=1024, intermediate_size=3072,
                  num_hidden_layers=28, num_attention_heads=16,
                  num_key_value_heads=8, head_dim=128,
                  tie_word_embeddings=True, **overrides)


def qwen3_8b(**overrides) -> ModelConfig:
    """Qwen3-8B (reference e2e_dense.md + mega 8B rows)."""
    return _build(hidden_size=4096, intermediate_size=12288,
                  num_hidden_layers=36, num_attention_heads=32,
                  num_key_value_heads=8, head_dim=128, **overrides)


def qwen3_32b(**overrides) -> ModelConfig:
    """Qwen3-32B (reference e2e prefill/decode + mega 32B rows)."""
    return _build(hidden_size=5120, intermediate_size=25600,
                  num_hidden_layers=64, num_attention_heads=64,
                  num_key_value_heads=8, head_dim=128, **overrides)


def qwen3_30b_a3b(**overrides) -> ModelConfig:
    """Qwen3-30B-A3B MoE: 128 experts, top-8, ~3B active params
    (reference Qwen3-MoE EP path, test_ep_moe_inference.py)."""
    return _build(hidden_size=2048, intermediate_size=0,
                  num_hidden_layers=48, num_attention_heads=32,
                  num_key_value_heads=4, head_dim=128,
                  num_experts=128, num_experts_per_tok=8,
                  moe_intermediate_size=768, **overrides)


def _build(**kw) -> ModelConfig:
    base = dict(vocab_size=151936, max_position_embeddings=40960,
                rope_theta=1_000_000.0, dtype=jnp.bfloat16)
    base.update(kw)
    return ModelConfig(**base)


def param_count(cfg: ModelConfig) -> int:
    """Approximate parameter count — delegates to the shared
    ``ModelConfig.param_split`` accounting (also used by
    ``parallel.plan_parallelism``)."""
    attn, mlp, embed = cfg.param_split()
    return (attn + mlp) * cfg.num_hidden_layers + embed


PRESETS = {
    "qwen3-0.6b": qwen3_0_6b,
    "qwen3-8b": qwen3_8b,
    "qwen3-32b": qwen3_32b,
    "qwen3-30b-a3b": qwen3_30b_a3b,
}
