"""KV cache (reference ``KV_Cache``,
python/triton_dist/models/kv_cache.py: per-layer (B, T, Hkv, D) tensors +
a host-side offset with ``inc_offset``).

Functional JAX shape: the cache is a pytree (list of per-layer (k, v)
pairs) threaded through the forward; ``KVCacheManager`` owns allocation,
sharding, and the offset bookkeeping the reference keeps on the module.
Head-sharded over TP by default (each rank caches its local heads — same
as the reference, which caches after the column-parallel KV projection);
``seq_shard=True`` shards the T dim instead for SP decode
(ops/flash_decode.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class KVCacheManager:
    def __init__(self, num_layers: int, batch: int, max_seq: int,
                 num_kv_heads: int, head_dim: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, seq_shard: bool = False):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.num_layers = num_layers
        self.batch, self.max_seq = batch, max_seq
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.seq_shard = seq_shard
        spec = P(None, axis) if seq_shard else P(None, None, axis)
        self.sharding = NamedSharding(mesh, spec)
        self.offset = 0  # host-side write position (reference kv_offset)

    def init(self):
        """Allocate the cache pytree: [(k, v)] * L."""
        shape = (self.batch, self.max_seq, self.num_kv_heads, self.head_dim)
        z = jnp.zeros(shape, self.dtype)
        return [
            (jax.device_put(z, self.sharding),
             jax.device_put(z, self.sharding))
            for _ in range(self.num_layers)
        ]

    def inc_offset(self, n: int) -> int:
        """Advance the write position (reference ``inc_offset``)."""
        self.offset += n
        assert self.offset <= self.max_seq, "KV cache overflow"
        return self.offset

    def reset(self):
        self.offset = 0


class PagedKVCacheManager:
    """Paged KV pools + block tables for SP decode serving.

    Integrates ``ops.flash_decode.gqa_fwd_batch_decode_paged`` (reference
    paged split-KV kernels, flash_decode.py:130-393) with a host-side
    slot allocator: each SP device owns a pool of ``slots_per_dev``
    physical (page_size, Hkv, D) pages and backs global positions
    [r*t_loc, (r+1)*t_loc) of every sequence. Sequences allocate their
    logical pages from per-device free lists (``alloc_seq``/``free_seq``
    — vLLM-style paging; the reference manages tables statically in its
    megakernel attn task).

    Layout contract (matches gqa_fwd_batch_decode_paged):
      pool_k/pool_v: (w*slots_per_dev, page_size, Hkv, D), dim 0 sharded.
      block_table:   (w, B, pages_per_seq_dev) int32, dim 0 sharded,
                     entries are device-LOCAL slot ids.
    """

    def __init__(self, num_layers: int, batch: int, page_size: int,
                 pages_per_seq_dev: int, num_kv_heads: int, head_dim: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, slots_per_dev: int | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.world = mesh.shape[axis]
        self.num_layers = num_layers
        self.batch = batch
        self.page_size = page_size
        self.pages_per_seq_dev = pages_per_seq_dev
        self.t_loc = page_size * pages_per_seq_dev
        self.max_seq = self.t_loc * self.world
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.slots_per_dev = (slots_per_dev if slots_per_dev is not None
                              else batch * pages_per_seq_dev)
        assert self.slots_per_dev >= pages_per_seq_dev, "pool too small"
        self.offset = 0
        # Host-side allocator state (numpy buffers shared verbatim with
        # the native allocator, csrc/kvpool/kvpool.cc): per-device free
        # STACKS + block tables + per-row owned flags. The serving hot
        # path (admit/evict) runs these through the C library when a
        # toolchain exists; the Python fallback below is bit-identical
        # (tests replay randomized traces through both).
        import numpy as np
        w, slots = self.world, self.slots_per_dev
        self._stack = np.empty((w, slots), np.int32)
        self._top = np.empty((w,), np.int32)
        self._table = np.zeros((w, batch, pages_per_seq_dev), np.int32)
        self._owned = np.zeros((batch,), np.uint8)
        from triton_dist_tpu.models import kv_native
        self._lib = kv_native._load()
        ok = (self._lib is not None
              and self._lib.tdt_kv_init(w, slots, self._stack,
                                        self._top) == 0)
        if not ok:  # no toolchain OR degenerate dims the C init rejects
            self._lib = None
            self._top[:] = slots
            self._stack[:] = np.arange(slots, dtype=np.int32)
        self._table_dev = None  # device copy, invalidated on alloc/free

    def _args(self):
        return (self.world, self.batch, self.pages_per_seq_dev,
                self.slots_per_dev, self._stack, self._top, self._table,
                self._owned)

    @staticmethod
    def _raise(rc: int, what: str):
        if rc == -1:
            raise RuntimeError(f"row {what}: not allocatable/freeable "
                               "(bad index or ownership state)")
        if rc == -2:
            raise RuntimeError(f"row {what}: device pool exhausted")

    # -- allocation (vLLM-style; host-side) --------------------------------
    def alloc_seq(self, b: int) -> None:
        """Reserve every logical page of row ``b`` on every device —
        all-or-nothing (exhaustion changes no state). (Lazy
        page-at-a-time allocation would also fit this table; the decode
        kernel only reads slots below kv_len.)"""
        if self._lib is not None:
            rc = self._lib.tdt_kv_alloc_seq(*self._args(), b)
        else:
            rc = self._py_alloc_seq(b)
        self._raise(rc, str(b))
        self._table_dev = None

    def _py_alloc_seq(self, b: int) -> int:
        if not (0 <= b < self.batch) or self._owned[b]:
            return -1
        pages = self.pages_per_seq_dev
        if any(self._top[r] < pages for r in range(self.world)):
            return -2  # check EVERY device first: no partial pops
        for r in range(self.world):
            for i in range(pages):
                self._top[r] -= 1
                self._table[r, b, i] = self._stack[r, self._top[r]]
        self._owned[b] = 1
        return 0

    def free_seq(self, b: int) -> None:
        if self._lib is not None:
            rc = self._lib.tdt_kv_free_seq(*self._args(), b)
        else:
            rc = self._py_free_seq(b)
        self._raise(rc, str(b))
        self._table_dev = None

    def _py_free_seq(self, b: int) -> int:
        if not (0 <= b < self.batch) or not self._owned[b]:
            return -1
        for r in range(self.world):
            for i in range(self.pages_per_seq_dev):
                self._stack[r, self._top[r]] = self._table[r, b, i]
                self._top[r] += 1
        self._owned[b] = 0
        return 0

    def owned_rows(self) -> list:
        """Rows currently holding an allocation."""
        return [int(b) for b in range(self.batch) if self._owned[b]]

    def alloc_many(self, rows) -> None:
        """Admission control: allocate a whole REQUEST of rows
        all-or-nothing — on any failure every row of this call is
        rolled back before raising."""
        import numpy as np
        rows = np.asarray(list(rows), np.int32)
        if self._lib is not None:
            rc = self._lib.tdt_kv_alloc_many(*self._args(), rows,
                                             len(rows))
        else:
            rc = 0
            done = []
            for b in rows:
                rc = self._py_alloc_seq(int(b))
                if rc != 0:
                    for k in done:
                        self._py_free_seq(k)
                    break
                done.append(int(b))
        self._raise(rc, str(list(map(int, rows))))
        self._table_dev = None

    def block_table(self) -> jax.Array:
        """Device copy of the (w, B, n_pages) table — pass this into
        jitted reads AND writes so table changes retrace instead of being
        baked in as constants (cached until the next alloc/free)."""
        if self._table_dev is None:
            self._table_dev = jax.device_put(
                jnp.asarray(self._table),
                NamedSharding(self.mesh, P(self.axis)))
        return self._table_dev

    # -- device state -------------------------------------------------------
    def init(self):
        """[(pool_k, pool_v)] * L, all slots zeroed."""
        shape = (self.world * self.slots_per_dev, self.page_size,
                 self.num_kv_heads, self.head_dim)
        sh = NamedSharding(self.mesh, P(self.axis))
        z = jax.device_put(jnp.zeros(shape, self.dtype), sh)
        # arrays are immutable — one zero transfer shared by all refs
        return [(z, z) for _ in range(self.num_layers)]

    @staticmethod
    def _addr(offset, page_size: int, n_pages: int):
        """THE one definition of the page-layout address math — every
        slot resolver below derives from it, so a layout change cannot
        silently diverge between write()/forward_sp/the XLA golden.
        Returns (device index r, local page lp, in-page row)."""
        offset = jnp.asarray(offset, jnp.int32)
        t_loc = page_size * n_pages
        return offset // t_loc, (offset % t_loc) // page_size, \
            offset % page_size

    @staticmethod
    def position_to_slot(table: jax.Array, offset, page_size: int,
                         slots_per_dev: int):
        """Global position(s) → (global pool rows, in-page row).

        ``offset`` may be a scalar (one decode step → rows (B,)) or a
        vector of T positions (golden reconstruction → rows (T, B)).
        """
        r, lp, inpage = PagedKVCacheManager._addr(offset, page_size,
                                                  table.shape[2])
        # expand_dims makes scalar r broadcast as (1,)+(B,)->(B,) and
        # vector r as (T,1)+(T,B)->(T,B).
        gslots = jnp.expand_dims(r * slots_per_dev, -1) + table[r, :, lp]
        return gslots, inpage

    @staticmethod
    def position_to_slot_rows(table: jax.Array, offsets, page_size: int,
                              slots_per_dev: int):
        """PER-ROW positions → (global pool rows (B,), in-page rows (B,)).

        Row b's position ``offsets[b]`` resolves through row b's OWN
        table lane (aligned indexing ``table[r[b], b, lp[b]]``) — the
        continuous-batching decode step where every sequence sits at a
        different write position (Engine.serve_stream paged mode).
        """
        r, lp, inpage = PagedKVCacheManager._addr(offsets, page_size,
                                                  table.shape[2])
        rows = jnp.arange(table.shape[1])
        gslots = r * slots_per_dev + table[r, rows, lp]
        return gslots, inpage

    def write(self, pools, layer: int, new_k: jax.Array, new_v: jax.Array,
              offset, table: jax.Array) -> list:
        """Scatter one decode step's (B, Hkv, D) K/V into the pools at
        global position ``offset`` (jit-compatible: pure gather/scatter
        on traced values).

        ``table``: pass :meth:`block_table`'s result through the jit
        boundary — closing over the host table would bake slot ids in as
        compile-time constants and go stale after ``free_seq``/
        ``alloc_seq`` (silent cross-sequence corruption).
        """
        pool_k, pool_v = pools[layer]
        gslots, inpage = self.position_to_slot(
            table, offset, self.page_size, self.slots_per_dev)
        pool_k = pool_k.at[gslots, inpage].set(new_k.astype(pool_k.dtype))
        pool_v = pool_v.at[gslots, inpage].set(new_v.astype(pool_v.dtype))
        out = list(pools)
        out[layer] = (pool_k, pool_v)
        return out

    def inc_offset(self, n: int) -> int:
        self.offset += n
        assert self.offset <= self.max_seq, "paged KV overflow"
        return self.offset

    def reset(self):
        self.offset = 0
