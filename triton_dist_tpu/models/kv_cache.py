"""KV cache (reference ``KV_Cache``,
python/triton_dist/models/kv_cache.py: per-layer (B, T, Hkv, D) tensors +
a host-side offset with ``inc_offset``).

Functional JAX shape: the cache is a pytree (list of per-layer (k, v)
pairs) threaded through the forward; ``KVCacheManager`` owns allocation,
sharding, and the offset bookkeeping the reference keeps on the module.
Head-sharded over TP by default (each rank caches its local heads — same
as the reference, which caches after the column-parallel KV projection);
``seq_shard=True`` shards the T dim instead for SP decode
(ops/flash_decode.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu import obs


class KVCacheManager:
    def __init__(self, num_layers: int, batch: int, max_seq: int,
                 num_kv_heads: int, head_dim: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, seq_shard: bool = False):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.num_layers = num_layers
        self.batch, self.max_seq = batch, max_seq
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.seq_shard = seq_shard
        spec = P(None, axis) if seq_shard else P(None, None, axis)
        self.sharding = NamedSharding(mesh, spec)
        self.offset = 0  # host-side write position (reference kv_offset)

    def init(self):
        """Allocate the cache pytree: [(k, v)] * L."""
        shape = (self.batch, self.max_seq, self.num_kv_heads, self.head_dim)
        z = jnp.zeros(shape, self.dtype)
        return [
            (jax.device_put(z, self.sharding),
             jax.device_put(z, self.sharding))
            for _ in range(self.num_layers)
        ]

    def inc_offset(self, n: int) -> int:
        """Advance the write position (reference ``inc_offset``)."""
        self.offset += n
        assert self.offset <= self.max_seq, "KV cache overflow"
        return self.offset

    def reset(self):
        self.offset = 0


class PagedKVCacheManager:
    """Paged KV pools + block tables for SP decode serving.

    Integrates ``ops.flash_decode.gqa_fwd_batch_decode_paged`` (reference
    paged split-KV kernels, flash_decode.py:130-393) with a host-side
    slot allocator: each SP device owns a pool of ``slots_per_dev``
    physical (page_size, Hkv, D) pages and backs global positions
    [r*t_loc, (r+1)*t_loc) of every sequence. Sequences allocate their
    logical pages from per-device free lists (``alloc_seq``/``free_seq``
    — vLLM-style paging; the reference manages tables statically in its
    megakernel attn task).

    Layout contract (matches gqa_fwd_batch_decode_paged):
      pool_k/pool_v: (w*phys_slots_per_dev, page_size, Hkv, D), dim 0
                     sharded. phys_slots_per_dev = slots_per_dev + 1:
                     the last physical page per device is the reserved
                     SENTINEL (stream sessions point unoccupied rows at
                     it) and lives OUTSIDE the accounted pool, so the
                     full slots_per_dev capacity stays allocatable.
      block_table:   (w, B, pages_per_seq_dev) int32, dim 0 sharded,
                     entries are device-LOCAL slot ids.
    """

    def __init__(self, num_layers: int, batch: int, page_size: int,
                 pages_per_seq_dev: int, num_kv_heads: int, head_dim: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, slots_per_dev: int | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.world = mesh.shape[axis]
        self.num_layers = num_layers
        self.batch = batch
        self.page_size = page_size
        self.pages_per_seq_dev = pages_per_seq_dev
        self.t_loc = page_size * pages_per_seq_dev
        self.max_seq = self.t_loc * self.world
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.slots_per_dev = (slots_per_dev if slots_per_dev is not None
                              else batch * pages_per_seq_dev)
        # Pools SMALLER than one whole row are legal: block-granular
        # stream sessions admit by blocks (ISSUE 6), and the
        # seq-granular alloc path fails a too-big request gracefully
        # ("device pool exhausted") rather than at construction.
        assert self.slots_per_dev >= 1, "pool too small"
        # The reserved sentinel page sits past the allocatable slots:
        # physical pools carry one extra row per device that no free
        # stack ever hands out, so pointing a frozen row at it costs
        # zero request capacity.
        self.phys_slots_per_dev = self.slots_per_dev + 1
        self.offset = 0
        # Host-side allocator state (numpy buffers shared verbatim with
        # the native allocator, csrc/kvpool/kvpool.cc): per-device free
        # STACKS + block tables + per-row owned flags. The serving hot
        # path (admit/evict) runs these through the C library when a
        # toolchain exists; the Python fallback below is bit-identical
        # (tests replay randomized traces through both).
        import numpy as np
        w, slots = self.world, self.slots_per_dev
        self._stack = np.empty((w, slots), np.int32)
        self._top = np.empty((w,), np.int32)
        self._table = np.zeros((w, batch, pages_per_seq_dev), np.int32)
        self._owned = np.zeros((batch,), np.uint8)
        from triton_dist_tpu.models import kv_native
        self._lib = kv_native._load()
        self._init_allocator()
        # Block-granular serving substrate (stream sessions): populated
        # by stream_setup(); the seq-granular API above never reads it.
        self._blockwise = False
        self.prefix = None           # PrefixCache when enabled
        self._sentinel = None        # (w,) slot ids unowned rows point at
        self._ref = np.zeros((w, slots), np.int32)
        self._row_blocks = np.zeros((batch,), np.int32)
        self._committed = np.zeros((w,), np.int64)
        self._row_commit = np.zeros((batch, w), np.int64)
        # Admission-time geometry rollback_position needs to restore
        # commitments EXACTLY: per (row, dev), the prompt-block count
        # (_row_base) and the committed decode tail (_row_tail0, an
        # immutable copy of the admission's _row_commit). A decode
        # block consumed commitment iff its per-device decode ordinal
        # sits below _row_tail0 — blocks allocate in order and the
        # commitment decrements while positive, so the rule is exact
        # and a rollback can never mint commitment a growth outside
        # the admission budget never consumed.
        self._row_base = np.zeros((batch, w), np.int64)
        self._row_tail0 = np.zeros((batch, w), np.int64)
        self._evicted_total = 0

    def _init_allocator(self) -> None:
        """(Re)initialize the free stacks + tables + ownership flags —
        the constructor's allocator state, also the pool reset between
        serving modes (seq-granular serve() vs block-granular stream
        sessions must never inherit each other's stack state)."""
        import numpy as np
        w, slots = self.world, self.slots_per_dev
        ok = (self._lib is not None
              and self._lib.tdt_kv_init(w, slots, self._stack,
                                        self._top) == 0)
        if not ok:  # no toolchain OR degenerate dims the C init rejects
            self._lib = None
            self._top[:] = slots
            self._stack[:] = np.arange(slots, dtype=np.int32)
        self._table[:] = 0
        self._owned[:] = 0
        self._table_dev = None  # device copy, invalidated on alloc/free

    def _args(self):
        return (self.world, self.batch, self.pages_per_seq_dev,
                self.slots_per_dev, self._stack, self._top, self._table,
                self._owned)

    @staticmethod
    def _raise(rc: int, what: str):
        if rc == -1:
            raise RuntimeError(f"row {what}: not allocatable/freeable "
                               "(bad index or ownership state)")
        if rc == -2:
            raise RuntimeError(f"row {what}: device pool exhausted")

    # -- allocation (vLLM-style; host-side) --------------------------------
    def alloc_seq(self, b: int) -> None:
        """Reserve every logical page of row ``b`` on every device —
        all-or-nothing (exhaustion changes no state). (Lazy
        page-at-a-time allocation would also fit this table; the decode
        kernel only reads slots below kv_len.)"""
        if self._lib is not None:
            rc = self._lib.tdt_kv_alloc_seq(*self._args(), b)
        else:
            rc = self._py_alloc_seq(b)
        self._raise(rc, str(b))
        self._table_dev = None

    def _py_alloc_seq(self, b: int) -> int:
        if not (0 <= b < self.batch) or self._owned[b]:
            return -1
        pages = self.pages_per_seq_dev
        if any(self._top[r] < pages for r in range(self.world)):
            return -2  # check EVERY device first: no partial pops
        for r in range(self.world):
            for i in range(pages):
                self._top[r] -= 1
                self._table[r, b, i] = self._stack[r, self._top[r]]
        self._owned[b] = 1
        return 0

    def free_seq(self, b: int) -> None:
        if self._lib is not None:
            rc = self._lib.tdt_kv_free_seq(*self._args(), b)
        else:
            rc = self._py_free_seq(b)
        self._raise(rc, str(b))
        self._table_dev = None

    def _py_free_seq(self, b: int) -> int:
        if not (0 <= b < self.batch) or not self._owned[b]:
            return -1
        for r in range(self.world):
            for i in range(self.pages_per_seq_dev):
                self._stack[r, self._top[r]] = self._table[r, b, i]
                self._top[r] += 1
        self._owned[b] = 0
        return 0

    def owned_rows(self) -> list:
        """Rows currently holding an allocation."""
        return [int(b) for b in range(self.batch) if self._owned[b]]

    def alloc_many(self, rows) -> None:
        """Admission control: allocate a whole REQUEST of rows
        all-or-nothing — on any failure every row of this call is
        rolled back before raising."""
        import numpy as np
        rows = np.asarray(list(rows), np.int32)
        if self._lib is not None:
            rc = self._lib.tdt_kv_alloc_many(*self._args(), rows,
                                             len(rows))
        else:
            rc = 0
            done = []
            for b in rows:
                rc = self._py_alloc_seq(int(b))
                if rc != 0:
                    for k in done:
                        self._py_free_seq(k)
                    break
                done.append(int(b))
        self._raise(rc, str(list(map(int, rows))))
        self._table_dev = None

    # -- block-granular serving substrate (stream sessions, ISSUE 6) ------
    # The seq-granular API above reserves whole max_seq rows (plain
    # serve()'s admission unit). Stream sessions instead run the pool
    # BLOCK-granular: a request is admitted when enough physical blocks
    # are free for its prompt + decode budget, its table lanes grow one
    # block at a time as decode crosses page boundaries, and its blocks
    # return to the pool the moment it retires. Full prompt blocks are
    # indexed in a cross-request prefix cache (models/prefix_cache.py):
    # refcounted sharing for hits, LRU eviction of refcount-zero blocks
    # when the free stacks run dry. One thread drives all of this (the
    # stream-session contract), so no locking.

    def reset_pool(self) -> None:
        """Return the pool to the constructor state: every slot free,
        tables zeroed, prefix index dropped, both serving modes clear.
        serve() and stream_setup() both start from here — the two
        admission disciplines must never inherit each other's stacks."""
        self._init_allocator()
        self._blockwise = False
        self.prefix = None
        self._sentinel = None
        self._ref[:] = 0
        self._row_blocks[:] = 0
        self._committed[:] = 0
        self._row_commit[:] = 0
        self._row_base[:] = 0
        self._row_tail0[:] = 0
        self.offset = 0
        self._emit_gauges()

    def stream_setup(self, prefix_cache: bool = True) -> None:
        """Reset the pool and enter block-granular mode.

        Points every row's table lanes at the per-device SENTINEL page:
        the shared decode step runs the per-row KV write for ALL rows
        (frozen rows included), so an unoccupied row needs somewhere
        harmless to write — the sentinel is that page (never read below
        any live row's kv_len, never indexed, never allocatable). This
        is what lets retired rows release their real blocks EAGERLY
        instead of holding them until a replacement is admitted. The
        sentinel is the reserved extra physical slot past the accounted
        pool (slot id ``slots_per_dev``), so it costs no capacity: a
        request needing every accounted slot still fits."""
        import numpy as np
        self.reset_pool()
        self._blockwise = True
        if prefix_cache:
            from triton_dist_tpu.models.prefix_cache import PrefixCache
            self.prefix = PrefixCache(self.world, self.page_size)
        self._sentinel = np.full((self.world,), self.slots_per_dev,
                                 np.int32)
        for b in range(self.batch):
            self._point_at_sentinel(b)
        self._table_dev = None
        self._emit_gauges()

    def _point_at_sentinel(self, b: int) -> None:
        self._table[:, b, :] = self._sentinel[:, None]

    def _pop_block(self, r: int) -> int:
        """One free block on device ``r``: the free stack first, then
        LRU eviction of a refcount-zero cached block."""
        if self._top[r] > 0:
            self._top[r] -= 1
            return int(self._stack[r, self._top[r]])
        victim = (self.prefix.evict_lru(r)
                  if self.prefix is not None else None)
        if victim is None:
            raise RuntimeError(f"device {r} pool exhausted")
        self._evicted_total += 1
        obs.counter("kv.blocks_evicted").inc()
        return victim

    def _push_block(self, r: int, slot: int) -> None:
        self._stack[r, self._top[r]] = slot
        self._top[r] += 1

    def _deref(self, r: int, slot: int) -> None:
        self._ref[r, slot] -= 1
        assert self._ref[r, slot] >= 0, f"double free: dev {r} slot {slot}"
        if self._ref[r, slot] == 0:
            if self.prefix is not None and self.prefix.is_indexed(r, slot):
                # Data stays resident for future hits; the block is now
                # the MRU eviction candidate.
                self.prefix.release(r, slot)
            else:
                self._push_block(r, slot)

    # -- admission arithmetic ---------------------------------------------
    def _block_lane(self, j: int):
        """Logical block ``j`` of a row → (device r, table lane lp).
        THE one spelling of the layout invariant — blocks stripe
        contiguously, ``pages_per_seq_dev`` per device; every demand
        tally below derives from it."""
        return j // self.pages_per_seq_dev, j % self.pages_per_seq_dev

    def _blocks_per_dev(self, j0: int, j1: int):
        """Per-device count of logical blocks [j0, j1) under
        ``_block_lane``'s striping."""
        import numpy as np
        out = np.zeros((self.world,), np.int64)
        js = np.arange(j0, j1) // self.pages_per_seq_dev
        if len(js):
            out += np.bincount(js, minlength=self.world)
        return out

    def need_per_dev(self, prompt_len: int, gen_len: int):
        """Worst-case block demand of one request, per device: blocks
        covering every position it will ever WRITE — prefill writes
        [0, L), decode steps write [L, L+G-1) (the budget's last token
        is sampled from the step that writes position L+G-2)."""
        last = max(prompt_len + max(gen_len, 1) - 1, prompt_len)
        n = -(-last // self.page_size)
        assert n <= self.pages_per_seq_dev * self.world, (
            f"request spans {n} blocks > max_seq capacity "
            f"(check prompt+gen_len <= max_seq first)")
        return self._blocks_per_dev(0, n)

    def available_per_dev(self):
        """Free-stack depth plus evictable (refcount-zero cached)
        blocks, per device — everything an admission could claim."""
        import numpy as np
        avail = self._top.astype(np.int64).copy()
        if self.prefix is not None:
            avail += np.asarray([self.prefix.evictable_count(r)
                                 for r in range(self.world)], np.int64)
        return avail

    def fits_pool(self, prompt_len: int, gen_len: int) -> bool:
        """Could this request EVER be admitted (empty pool)? False
        means the submit must be rejected as unservable, not queued
        (it would deadlock the admission queue). The sentinel lives
        outside the accounted pool, so every slot counts."""
        return bool((self.need_per_dev(prompt_len, gen_len)
                     <= self.slots_per_dev).all())

    def can_admit(self, prompt_len: int, gen_len: int,
                  extra=None) -> bool:
        """Admission control: enough blocks free (or evictable) on
        every device for this request's worst-case demand, net of what
        is already committed to live rows' un-allocated decode tails
        (and of ``extra`` — same-batch admissions not yet executed).
        Conservative: prefix-cache hits can only reduce the true
        demand, never raise it."""
        avail = self.available_per_dev() - self._committed
        if extra is not None:
            avail = avail - extra
        return bool((avail >= self.need_per_dev(prompt_len,
                                                gen_len)).all())

    # -- request lifecycle -------------------------------------------------
    def prefix_hashes(self, prompt) -> list | None:
        """Full block-hash chain for ``prompt`` (``None`` without a
        prefix cache). Admission walks the chain three times
        (probe → admit → register); computing it once here and passing
        it down keeps long-preamble admissions off the sha1 treadmill."""
        if self.prefix is None:
            return None
        return self.prefix.block_hashes(prompt)

    def prefix_lookup_blocks(self, prompt_len: int) -> int:
        """Blocks eligible for a prefix-cache lookup: every FULL
        prompt block except the last one of an exactly page-aligned
        prompt, which is always recomputed (the admission program
        needs the final position's logits). The single home of that
        trim rule — probe, admit, and the obs lookup counter all
        derive from it."""
        n = prompt_len // self.page_size
        if n and prompt_len % self.page_size == 0:
            n -= 1
        return n

    def prefix_probe(self, prompt, hashes=None) -> int:
        """Upper bound on cache-hit BLOCKS for ``prompt`` (stateless;
        the engine sizes the suffix admission program off this before
        committing to the hits)."""
        if self.prefix is None:
            return 0
        if hashes is None:
            hashes = self.prefix.block_hashes(prompt)
        return self.prefix.probe(
            hashes[:self.prefix_lookup_blocks(len(prompt))])

    def admit_row(self, b: int, prompt, gen_budget: int = 0,
                  use_hits: int | None = None, hashes=None) -> int:
        """Block-granular admission of ``prompt`` into row ``b``:

        1. map up to ``use_hits`` cached prefix blocks into the row's
           lanes (refcounted, shared, read-only);
        2. allocate private blocks for the rest of the prompt;
        3. commit (without allocating) the decode-tail blocks the
           ``gen_budget`` may still demand, so a later admission cannot
           starve this row mid-decode.

        All-or-nothing: on exhaustion every hit ref is rolled back and
        the row's lanes return to the sentinel. Returns the number of
        prefix TOKENS served from cache (a page multiple)."""
        import numpy as np
        assert self._blockwise, "admit_row needs stream_setup() first"
        assert self._row_blocks[b] == 0, f"row {b} already holds blocks"
        L = len(prompt)
        page = self.page_size
        hits, n_lookup = [], 0
        if self.prefix is not None:
            if hashes is None:
                hashes = self.prefix.block_hashes(prompt)
            hashes = hashes[:self.prefix_lookup_blocks(L)]
            n_lookup = len(hashes)
            hits = self.prefix.resolve(hashes, max_hits=use_hits)
        k = len(hits)
        n_prompt = -(-L // page)
        last = max(L + max(gen_budget, 1) - 1, L)
        n_total = max(n_prompt, -(-last // page))
        # Map the hits FIRST (claiming them out of the evictable pool)
        # so the availability check sees the exact post-hit state.
        for j, (r, slot) in enumerate(hits):
            rj, lp = self._block_lane(j)
            assert r == rj, "prefix index device/layout mismatch"
            if self._ref[r, slot] == 0:
                self.prefix.claim(r, slot)
            self._ref[r, slot] += 1
            self._table[r, b, lp] = slot
        need = self._blocks_per_dev(k, n_total)
        avail = self.available_per_dev() - self._committed
        if np.any(avail < need):
            for j, (r, slot) in enumerate(hits):    # roll back
                self._deref(r, slot)
            self._point_at_sentinel(b)
            self._table_dev = None
            raise RuntimeError(
                f"row {b}: device pool exhausted "
                f"(short {int(np.max(need - avail))} block(s); "
                f"{int(self._committed.sum())} committed to live rows)")
        for j in range(k, n_prompt):
            r, lp = self._block_lane(j)
            slot = self._pop_block(r)
            self._ref[r, slot] = 1
            self._table[r, b, lp] = slot
        tail = self._blocks_per_dev(n_prompt, n_total)
        self._row_commit[b] = tail
        self._committed += tail
        self._row_base[b] = self._blocks_per_dev(0, n_prompt)
        self._row_tail0[b] = tail
        self._row_blocks[b] = n_prompt
        if self.prefix is not None:     # account only admissions that
            self.prefix.account(n_lookup, k)    # actually succeeded
        self._table_dev = None
        self._emit_gauges()
        return k * page

    def ensure_position(self, b: int, pos: int) -> bool:
        """Grow row ``b``'s allocation to cover write position ``pos``
        (called before each decode step). Returns True when new blocks
        were allocated — the caller must refresh its device table.

        Grows one block per step under plain decode; a SPECULATIVE
        burst (ISSUE 13) writes up to k+1 positions per step and may
        cross several page boundaries at once, so growth allocates
        every block from the current edge through ``pos``'s block.
        Each allocation consumes the row's decode commitment where one
        exists; ``rollback_position`` restores exactly the commitments
        growth consumed (the per-device decode-ordinal rule there)."""
        j = pos // self.page_size
        n = int(self._row_blocks[b])
        if j < n:
            return False
        for jj in range(n, j + 1):
            r, lp = self._block_lane(jj)
            slot = self._pop_block(r)
            self._ref[r, slot] = 1
            self._table[r, b, lp] = slot
            self._row_blocks[b] = jj + 1
            if self._row_commit[b, r] > 0:   # consume the commitment
                self._row_commit[b, r] -= 1
                self._committed[r] -= 1
        self._table_dev = None
        self._emit_gauges()
        return True

    def rollback_position(self, b: int, pos: int) -> bool:
        """Shrink row ``b``'s allocation back to the blocks covering
        write positions [0, ``pos``] — the rejected-tail rewind of a
        speculative burst (ISSUE 13): blocks allocated for draft
        positions past the accepted prefix return to the pool (deref —
        a decode-tail block is always private, so this is a free), the
        lanes point back at the sentinel, and the commitments those
        allocations consumed are restored so a later admission still
        cannot starve this row's remaining budget. Returns True when
        blocks were freed — the caller must refresh its device table.
        Stale K/V inside the KEPT tail block needs no rewind: positions
        past the committed offset are never exposed by any mask before
        the next step overwrites them."""
        keep = int(pos) // self.page_size + 1
        n = int(self._row_blocks[b])
        if n <= keep:
            return False
        for jj in range(keep, n):
            r, lp = self._block_lane(jj)
            self._deref(r, int(self._table[r, b, lp]))
            self._table[r, b, lp] = self._sentinel[r]
            # This block consumed commitment iff its per-device decode
            # ordinal sits below the admission tail (allocation order
            # is monotone, so the rule is exact — a block grown PAST
            # the budget restores nothing).
            d = lp - int(self._row_base[b, r])
            if 0 <= d < int(self._row_tail0[b, r]):
                self._row_commit[b, r] += 1
                self._committed[r] += 1
        self._row_blocks[b] = keep
        self._table_dev = None
        self._emit_gauges()
        return True

    def release_row(self, b: int) -> None:
        """Eager retirement: deref every block (shared blocks drop a
        ref; indexed refcount-zero blocks stay cached and evictable;
        private blocks return to the free stack), release the row's
        remaining decode commitment, and point its lanes back at the
        sentinel so frozen-row writes stay harmless."""
        for j in range(int(self._row_blocks[b])):
            r, lp = self._block_lane(j)
            self._deref(r, int(self._table[r, b, lp]))
        self._committed -= self._row_commit[b]
        self._row_commit[b] = 0
        self._row_base[b] = 0
        self._row_tail0[b] = 0
        self._row_blocks[b] = 0
        self._point_at_sentinel(b)
        self._table_dev = None
        self._emit_gauges()

    def register_prefix(self, b: int, tokens, hashes=None) -> int:
        """Index row ``b``'s full PROMPT blocks in the prefix cache
        (called once the admission prefill has been dispatched — the
        pool arrays carrying the data are threaded through the session
        caches, so a later hit reads exactly what was computed). The
        partial tail block is mutable (decode writes it) and is never
        indexed; full blocks are immutable for their pool lifetime —
        the copy-on-write discipline with the copy statically
        unreachable. Returns how many blocks were newly indexed."""
        if self.prefix is None:
            return 0
        n_full = min(len(tokens) // self.page_size,
                     int(self._row_blocks[b]))
        if hashes is None:
            hashes = self.prefix.block_hashes(tokens)
        new = 0
        for j in range(n_full):
            r, lp = self._block_lane(j)
            new += bool(self.prefix.register(
                hashes[j], r, int(self._table[r, b, lp])))
        return new

    # -- introspection -----------------------------------------------------
    def block_audit(self) -> dict:
        """Pool accounting snapshot (the quick-tier leak audit: after
        every request retires, free + evictable must equal the whole
        pool — a stranded block is a slow OOM). The sentinel pages are
        outside the accounted pool and never appear here."""
        free = int(self._top.sum())
        evictable = (sum(self.prefix.evictable_count(r)
                         for r in range(self.world))
                     if self.prefix is not None else 0)
        total = self.world * self.slots_per_dev
        return {"free": free, "evictable": evictable,
                "active": total - free - evictable,
                "committed": int(self._committed.sum()),
                "evicted_total": self._evicted_total,
                "total": total}

    def _emit_gauges(self) -> None:
        if not obs.enabled():
            return
        a = self.block_audit()
        obs.gauge("kv.blocks_free").set(a["free"])
        obs.gauge("kv.blocks_cached").set(a["evictable"])
        obs.gauge("kv.blocks_active").set(a["active"])
        if a["total"]:
            obs.gauge("kv.block_utilization").set(
                round(1.0 - (a["free"] + a["evictable"]) / a["total"], 4))

    def block_table(self) -> jax.Array:
        """Device copy of the (w, B, n_pages) table — pass this into
        jitted reads AND writes so table changes retrace instead of being
        baked in as constants (cached until the next alloc/free)."""
        if self._table_dev is None:
            self._table_dev = jax.device_put(
                jnp.asarray(self._table),
                NamedSharding(self.mesh, P(self.axis)))
        return self._table_dev

    # -- device state -------------------------------------------------------
    def init(self):
        """[(pool_k, pool_v)] * L, all slots zeroed. The +1 physical
        slot per device is the reserved sentinel page; consumers derive
        the slot stride from the array shape, never from
        ``slots_per_dev``."""
        shape = (self.world * self.phys_slots_per_dev, self.page_size,
                 self.num_kv_heads, self.head_dim)
        sh = NamedSharding(self.mesh, P(self.axis))
        z = jax.device_put(jnp.zeros(shape, self.dtype), sh)
        # arrays are immutable — one zero transfer shared by all refs
        return [(z, z) for _ in range(self.num_layers)]

    @staticmethod
    def _addr(offset, page_size: int, n_pages: int):
        """THE one definition of the page-layout address math — every
        slot resolver below derives from it, so a layout change cannot
        silently diverge between write()/forward_sp/the XLA golden.
        Returns (device index r, local page lp, in-page row)."""
        offset = jnp.asarray(offset, jnp.int32)
        t_loc = page_size * n_pages
        return offset // t_loc, (offset % t_loc) // page_size, \
            offset % page_size

    @staticmethod
    def position_to_slot(table: jax.Array, offset, page_size: int,
                         slots_per_dev: int):
        """Global position(s) → (global pool rows, in-page row).

        ``offset`` may be a scalar (one decode step → rows (B,)) or a
        vector of T positions (golden reconstruction → rows (T, B)).
        """
        r, lp, inpage = PagedKVCacheManager._addr(offset, page_size,
                                                  table.shape[2])
        # expand_dims makes scalar r broadcast as (1,)+(B,)->(B,) and
        # vector r as (T,1)+(T,B)->(T,B).
        gslots = jnp.expand_dims(r * slots_per_dev, -1) + table[r, :, lp]
        return gslots, inpage

    @staticmethod
    def gathered_view(pool: jax.Array, table: jax.Array, world: int):
        """Contiguous (B, T, Hkv, D) view of one pooled layer via table
        gathers — THE shared reconstruction consumed by both the
        "gathered"/xla paged decode (ops/flash_decode.py) and the paged
        chunked-prefill attention (dense.forward_sp), so the pool-gather
        geometry cannot diverge between the read paths. Positions past
        a row's live length resolve to sentinel/stale pages the callers'
        kv_len masks never expose. Known cost: O(max_seq) gather, like
        _paged_scatter's staging (optimization candidate). Callers apply
        their own sharding constraint to the result."""
        page_size = pool.shape[1]
        t_total = page_size * table.shape[2] * world
        posn = jnp.arange(t_total, dtype=jnp.int32)
        g, ip = PagedKVCacheManager.position_to_slot(
            table, posn, page_size, pool.shape[0] // world)
        return pool[g, ip[:, None]].transpose(1, 0, 2, 3)

    @staticmethod
    def position_to_slot_rows(table: jax.Array, offsets, page_size: int,
                              slots_per_dev: int):
        """PER-ROW positions → (global pool rows (B,), in-page rows (B,)).

        Row b's position ``offsets[b]`` resolves through row b's OWN
        table lane (aligned indexing ``table[r[b], b, lp[b]]``) — the
        continuous-batching decode step where every sequence sits at a
        different write position (Engine.serve_stream paged mode).
        """
        r, lp, inpage = PagedKVCacheManager._addr(offsets, page_size,
                                                  table.shape[2])
        rows = jnp.arange(table.shape[1])
        gslots = r * slots_per_dev + table[r, rows, lp]
        return gslots, inpage

    def write(self, pools, layer: int, new_k: jax.Array, new_v: jax.Array,
              offset, table: jax.Array) -> list:
        """Scatter one decode step's (B, Hkv, D) K/V into the pools at
        global position ``offset`` (jit-compatible: pure gather/scatter
        on traced values).

        ``table``: pass :meth:`block_table`'s result through the jit
        boundary — closing over the host table would bake slot ids in as
        compile-time constants and go stale after ``free_seq``/
        ``alloc_seq`` (silent cross-sequence corruption).
        """
        pool_k, pool_v = pools[layer]
        gslots, inpage = self.position_to_slot(
            table, offset, self.page_size, self.phys_slots_per_dev)
        pool_k = pool_k.at[gslots, inpage].set(new_k.astype(pool_k.dtype))
        pool_v = pool_v.at[gslots, inpage].set(new_v.astype(pool_v.dtype))
        out = list(pools)
        out[layer] = (pool_k, pool_v)
        return out

    def inc_offset(self, n: int) -> int:
        self.offset += n
        assert self.offset <= self.max_seq, "paged KV overflow"
        return self.offset

    def reset(self):
        self.offset = 0
