"""KV cache (reference ``KV_Cache``,
python/triton_dist/models/kv_cache.py: per-layer (B, T, Hkv, D) tensors +
a host-side offset with ``inc_offset``).

Functional JAX shape: the cache is a pytree (list of per-layer (k, v)
pairs) threaded through the forward; ``KVCacheManager`` owns allocation,
sharding, and the offset bookkeeping the reference keeps on the module.
Head-sharded over TP by default (each rank caches its local heads — same
as the reference, which caches after the column-parallel KV projection);
``seq_shard=True`` shards the T dim instead for SP decode
(ops/flash_decode.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class KVCacheManager:
    def __init__(self, num_layers: int, batch: int, max_seq: int,
                 num_kv_heads: int, head_dim: int,
                 mesh: Mesh | None = None, axis: str = "tp",
                 dtype=jnp.bfloat16, seq_shard: bool = False):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        self.mesh, self.axis = mesh, axis
        self.num_layers = num_layers
        self.batch, self.max_seq = batch, max_seq
        self.num_kv_heads, self.head_dim = num_kv_heads, head_dim
        self.dtype = dtype
        self.seq_shard = seq_shard
        spec = P(None, axis) if seq_shard else P(None, None, axis)
        self.sharding = NamedSharding(mesh, spec)
        self.offset = 0  # host-side write position (reference kv_offset)

    def init(self):
        """Allocate the cache pytree: [(k, v)] * L."""
        shape = (self.batch, self.max_seq, self.num_kv_heads, self.head_dim)
        z = jnp.zeros(shape, self.dtype)
        return [
            (jax.device_put(z, self.sharding),
             jax.device_put(z, self.sharding))
            for _ in range(self.num_layers)
        ]

    def inc_offset(self, n: int) -> int:
        """Advance the write position (reference ``inc_offset``)."""
        self.offset += n
        assert self.offset <= self.max_seq, "KV cache overflow"
        return self.offset

    def reset(self):
        self.offset = 0
