"""Speculative decoding: drafters + acceptance for the shared batch.

ISSUE 13 / ROADMAP item 3 — the scheduler's "exactly one token per row
per pump iteration" invariant generalized to 0..k tokens. A DRAFTER
proposes up to ``k`` continuation tokens per live row; the target model
scores every draft position in ONE widened decode step (the verify
window, ``Engine._build_spec_verify_step`` — compiled per k like the
chunked-prefill programs); the longest draft prefix matching the
target's own greedy argmax commits atomically, plus the target's next
token after it (the "bonus" token — under greedy acceptance the emitted
stream is BIT-IDENTICAL to non-speculative decode, which is the whole
acceptance bar: a verify window's logits equal k+1 sequential decode
steps' logits, and every emitted token is the target's argmax).

Two drafters:

- :class:`NGramDrafter` (default, model-free): prompt-lookup /
  n-gram continuation — the most recent earlier occurrence of the
  row's trailing n-gram proposes the tokens that followed it. Zero
  model cost, so it is measurable on CPU (bench.py ``serving_spec``);
  it wins exactly on repetition-heavy workloads (code, templated
  text, self-repeating greedy decodes).
- :class:`ModelDrafter`: a small model (e.g. ``presets.qwen3_0_6b``
  drafting for an 8B/32B target — :func:`draft_model_from_preset`
  shares the preset machinery) runs its own per-row KV cache in
  lockstep with the committed stream: each burst it first ingests the
  newly committed tokens (catch-up), then autoregressively drafts k
  tokens into scratch cache positions the next catch-up overwrites.

:class:`SpecState` owns the per-row bookkeeping a
``StreamSession`` needs (drafter lifecycle, remaining-budget clamps so
a burst can never write past the row's admission commitment or
max_seq) and the pure acceptance rule (:func:`accept_greedy`).
Greedy-only by design: ``Engine(spec=...)`` refuses stochastic
sampling — correct spec sampling needs rejection-resampling, and the
bit-identity guarantee is the contract everything here is tested
against (docs/serving.md "Speculative decoding").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu import obs

__all__ = ["DEFAULT_K", "SpecConfig", "NGramDrafter", "ModelDrafter",
           "SpecState", "accept_greedy", "draft_model_from_preset"]

#: Default maximum draft tokens per row per verify step.
DEFAULT_K = 4


class SpecConfig:
    """Speculative-decoding configuration for ``Engine(spec=...)``.

    ``k``: max draft tokens per row per step (``TDT_SPEC_K`` env
    overrides; each verify step emits 1..k+1 tokens per live row).
    ``drafter``: ``"ngram"`` (model-free prompt lookup, default) or
    ``"model"`` (requires ``draft_model`` + ``draft_params`` — a small
    model sharing the target's vocabulary).
    ``ngram_n``: longest trailing n-gram the lookup drafter matches
    (falls back through shorter n-grams down to 1).
    ``TDT_SPEC=0`` disables speculation process-wide (the engine then
    behaves exactly as ``spec=None``) — the kill switch is env so a
    misbehaving drafter can be turned off without a redeploy.
    """

    def __init__(self, k: int | None = None, drafter: str = "ngram",
                 ngram_n: int = 3, draft_model=None, draft_params=None,
                 draft_mode: str = "xla_ar"):
        import os
        if k is None:
            k = obs.env_int("TDT_SPEC_K", DEFAULT_K, minimum=1)
        if k < 1:
            raise ValueError(f"SpecConfig.k must be >= 1: {k}")
        if drafter not in ("ngram", "model"):
            raise ValueError(
                f"SpecConfig.drafter must be 'ngram' or 'model': "
                f"{drafter!r}")
        if drafter == "model" and (draft_model is None
                                   or draft_params is None):
            raise ValueError(
                "drafter='model' needs draft_model= and draft_params= "
                "(a small preset sharing the target's vocab — "
                "spec.draft_model_from_preset)")
        if ngram_n < 1:
            raise ValueError(f"SpecConfig.ngram_n must be >= 1: "
                             f"{ngram_n}")
        self.k = int(k)
        self.drafter = drafter
        self.ngram_n = int(ngram_n)
        self.draft_model = draft_model
        self.draft_params = draft_params
        self.draft_mode = draft_mode
        self.enabled = os.environ.get("TDT_SPEC", "1").strip() != "0"


def draft_model_from_preset(name: str, mesh=None, axis: str = "tp",
                            impl: str = "xla", **overrides):
    """Build a drafter model from a named preset (``models.presets``)
    — the qwen3-0.6b-drafts-for-qwen3-8b/32b pairing the reference's
    model menu implies. Returns the (uninitialized) model; load or
    init params with the same checkpoint machinery as any model, then
    pass both to ``SpecConfig(drafter="model", ...)``."""
    from triton_dist_tpu.models import presets
    from triton_dist_tpu.models.dense import DenseLLM
    if name not in presets.PRESETS:
        raise ValueError(f"unknown preset {name!r} "
                         f"(known: {sorted(presets.PRESETS)})")
    cfg = presets.PRESETS[name](**overrides)
    return DenseLLM(cfg, mesh=mesh, axis=axis, impl=impl)


def accept_greedy(draft: list, target: np.ndarray) -> tuple:
    """The greedy acceptance rule for one row: ``target`` holds the
    verify window's argmax at positions 0..k (``target[i]`` = the
    target model's next token after consuming draft position i-1, with
    ``target[0]`` following the last committed token). Returns
    ``(accepted, emitted)`` — the longest prefix of ``draft`` the
    target reproduces, and the tokens the row emits this burst
    (``accepted + 1``: the accepted prefix re-emitted from the
    target's own argmax, plus the bonus token after it). Bit-identity
    with sequential decode is by construction: every emitted token IS
    the target's argmax given exactly the committed prefix."""
    a = 0
    while a < len(draft) and int(draft[a]) == int(target[a]):
        a += 1
    return a, [int(t) for t in target[:a + 1]]


class NGramDrafter:
    """Model-free prompt-lookup drafter.

    Per row, the committed token stream (prompt + emitted) is indexed
    by its n-grams (for n = ``ngram_n`` down to 1, most recent
    occurrence wins): a draft looks up the stream's trailing n-gram
    and proposes the tokens that followed its previous occurrence.
    O(ngram_n) per committed token, O(ngram_n + k) per draft — cheap
    enough that a miss (empty draft) costs nothing but the lookup."""

    def __init__(self, k: int, ngram_n: int = 3):
        self.k = int(k)
        self.n = int(ngram_n)
        self._hist: dict[int, list] = {}
        self._index: dict[int, list] = {}   # row -> [dict per n]

    def start_row(self, row: int, prompt) -> None:
        self._hist[row] = []
        self._index[row] = [dict() for _ in range(self.n)]
        self.observe(row, prompt)

    def retire_row(self, row: int) -> None:
        self._hist.pop(row, None)
        self._index.pop(row, None)

    def observe(self, row: int, tokens) -> None:
        """Append committed tokens; index the n-grams that now have a
        known continuation (the gram ENDING one before each new token,
        so a lookup always finds a non-empty continuation)."""
        h = self._hist[row]
        idx = self._index[row]
        for t in tokens:
            h.append(int(t))
            p = len(h) - 1          # position of the continuation t
            for n in range(1, self.n + 1):
                if p >= n:
                    idx[n - 1][tuple(h[p - n:p])] = p

    def draft_batch(self, rows, kmax: dict) -> dict:
        return {r: self._draft(r, kmax[r]) for r in rows}

    def _draft(self, row: int, kmax: int) -> list:
        h = self._hist[row]
        idx = self._index[row]
        kmax = min(self.k, kmax)
        if kmax <= 0:
            return []
        for n in range(min(self.n, len(h)), 0, -1):
            p = idx[n - 1].get(tuple(h[-n:]))
            if p is not None and p < len(h):
                return h[p:p + kmax]
        return []


class ModelDrafter:
    """Small-model drafter: its own per-row KV cache follows the
    COMMITTED stream (never the drafts).

    Admission prefills the prompt through a bucketed batch-1 program
    scattered into the row's lane (the engine's admission pattern);
    each ``draft_batch`` first CATCHES UP — ingesting the tokens the
    target committed since the last draft, one shared (B,)-row step
    per token (rows with nothing pending ride along frozen; their
    scratch writes are overwritten before any mask exposes them) —
    then drafts autoregressively from the last catch-up step's argmax,
    writing k-1 scratch positions the next catch-up overwrites. The
    drafter's committed offset therefore always equals the target's,
    which is what makes its proposals conditionally correct."""

    def __init__(self, model, params, k: int, batch: int, max_seq: int,
                 mode: str = "xla_ar"):
        from triton_dist_tpu.models.kv_cache import KVCacheManager
        self.model, self.params = model, params
        self.k = int(k)
        self.mode = mode
        c = model.config
        self.max_seq = int(max_seq)
        self.kv = KVCacheManager(
            c.num_hidden_layers, batch, max_seq, c.num_key_value_heads,
            c.head_dim, mesh=model.mesh, axis=model.axis, dtype=c.dtype)
        self.caches = self.kv.init()
        self.batch = batch
        self._off = [0] * batch          # committed ingest position
        self._pending: dict[int, list] = {}
        self._seed: dict[int, int] = {}  # argmax after last catch-up
        self._step = None
        self._admit = None

    # -- jitted programs ---------------------------------------------------
    def _build_step(self):
        model, mode = self.model, self.mode

        @jax.jit
        def step(params, caches, token, offsets):
            logits, caches = model.forward(params, token[:, None],
                                           caches, offsets, mode=mode)
            return (jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32),
                    caches)
        return step

    def _build_admit(self):
        model, mode = self.model, self.mode

        @jax.jit
        def admit(params, caches, ids, row):
            lb = ids.shape[1]
            small = [(jnp.zeros((1, lb) + ck.shape[2:], ck.dtype),
                      jnp.zeros((1, lb) + cv.shape[2:], cv.dtype))
                     for ck, cv in caches]
            _, small = model.forward(params, ids, small, 0, mode=mode)
            out = []
            for (ck, cv), (sk, sv) in zip(caches, small):
                ck = jax.lax.dynamic_update_slice(ck, sk, (row, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(cv, sv, (row, 0, 0, 0))
                out.append((ck, cv))
            return out
        return admit

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    # -- row lifecycle -----------------------------------------------------
    def start_row(self, row: int, prompt) -> None:
        prompt = [int(t) for t in prompt]
        assert len(prompt) <= self.max_seq, "draft cache too small"
        if self._admit is None:
            self._admit = self._build_admit()
        lb = min(self._bucket(len(prompt)), self.max_seq)
        ids = jnp.asarray([prompt + [0] * (lb - len(prompt))], jnp.int32)
        self.caches = self._admit(self.params, self.caches, ids,
                                  jnp.int32(row))
        self._off[row] = len(prompt)
        self._pending[row] = []
        self._seed.pop(row, None)

    def retire_row(self, row: int) -> None:
        self._pending.pop(row, None)
        self._seed.pop(row, None)

    def observe(self, row: int, tokens) -> None:
        self._pending[row].extend(int(t) for t in tokens)

    # -- drafting ----------------------------------------------------------
    def draft_batch(self, rows, kmax: dict) -> dict:
        if self._step is None:
            self._step = self._build_step()
        rows = [r for r in rows]
        # Phase 1 — catch-up: ingest pending committed tokens, one
        # shared step per token. A row whose pending ran out rides
        # along frozen (offset pinned; its scratch write at its own
        # next position is overwritten by its next real ingest before
        # any consumed output attends it).
        while any(self._pending.get(r) for r in rows):
            toks = np.zeros((self.batch,), np.int32)
            active = []
            for r in rows:
                pend = self._pending.get(r)
                if pend:
                    toks[r] = pend.pop(0)
                    active.append(r)
                else:
                    toks[r] = self._seed.get(r, 0)
            nxt, self.caches = self._step(
                self.params, self.caches, jnp.asarray(toks),
                jnp.asarray(self._off, jnp.int32))
            nxt = np.asarray(nxt)
            for r in active:
                self._off[r] += 1
                if not self._pending[r]:
                    self._seed[r] = int(nxt[r])
        # Phase 2 — autoregressive drafting from each row's seed into
        # scratch positions (committed offsets NOT advanced; the next
        # catch-up overwrites these writes).
        lim = {r: min(self.k, kmax[r], self.max_seq - 1 - self._off[r])
               for r in rows}
        k_step = max((lim[r] for r in rows), default=0)
        drafts = {r: [] for r in rows}
        if k_step <= 0:
            return {r: [] for r in rows}
        cur = np.zeros((self.batch,), np.int32)
        for r in rows:
            if lim[r] >= 1 and r in self._seed:
                drafts[r].append(self._seed[r])
            cur[r] = self._seed.get(r, 0)
        for i in range(1, k_step):
            nxt, self.caches = self._step(
                self.params, self.caches, jnp.asarray(cur),
                jnp.asarray(self._off, jnp.int32) + jnp.int32(i - 1))
            nxt = np.asarray(nxt)
            for r in rows:
                if len(drafts[r]) == i and lim[r] > i:
                    drafts[r].append(int(nxt[r]))
            cur = nxt.astype(np.int32)
        return drafts


class SpecState:
    """Per-session speculative-decoding state a ``StreamSession``
    drives: drafter lifecycle + the per-row budget/room clamps that
    keep a burst's writes inside the row's admission commitment and
    ``max_seq`` (docs/serving.md "Speculative decoding")."""

    def __init__(self, cfg: SpecConfig, batch: int, max_seq: int):
        self.cfg = cfg
        self.max_seq = int(max_seq)
        self._budget: dict[int, int | None] = {}
        if cfg.drafter == "model":
            self.drafter = ModelDrafter(cfg.draft_model,
                                        cfg.draft_params, cfg.k, batch,
                                        max_seq, mode=cfg.draft_mode)
        else:
            self.drafter = NGramDrafter(cfg.k, cfg.ngram_n)

    def start_row(self, row: int, prompt, first_token: int,
                  gen_budget: int | None) -> None:
        """Row admitted: seed the drafter with prompt + the admission's
        first token; ``gen_budget`` (tokens the row may still emit,
        INCLUDING the first token) bounds every later burst so spec
        writes never outrun the admission's block commitment."""
        self.drafter.start_row(row, prompt)
        self.drafter.observe(row, [int(first_token)])
        self._budget[row] = (int(gen_budget) - 1
                             if gen_budget else None)

    def observe(self, row: int, tokens) -> None:
        self.drafter.observe(row, tokens)
        if self._budget.get(row) is not None:
            self._budget[row] -= len(tokens)

    def retire_row(self, row: int) -> None:
        self.drafter.retire_row(row)
        self._budget.pop(row, None)

    def plan(self, rows, host_off) -> dict:
        """Clamped drafts per live row. A burst with n drafts writes
        positions offset..offset+n and emits <= n+1 tokens, so n is
        capped at (remaining budget - 1) — keeping writes inside the
        committed positions [0, L+G-2] — and at max_seq-1-offset."""
        kmax = {}
        for r in rows:
            room = self.max_seq - 1 - int(host_off[r])
            bud = self._budget.get(r)
            lim = room if bud is None else min(bud - 1, room)
            kmax[r] = max(0, min(self.cfg.k, lim))
        drafts = self.drafter.draft_batch(rows, kmax)
        return {r: list(drafts.get(r) or [])[:kmax[r]] for r in rows}
