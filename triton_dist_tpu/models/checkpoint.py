"""Checkpoint save/restore for model params (+ optional engine state).

The reference has NO checkpointing (SURVEY.md §5 "Checkpoint/resume:
none — models load HF safetensors at init; no saving"). On TPU this is
table stakes for long-running serving/finetune jobs, and the ecosystem
tool is Orbax: sharded params save/restore with the layout preserved, so
a restore onto the same mesh needs no resharding.
"""

from __future__ import annotations

import os


def save_params(path: str, params) -> str:
    """Write a params pytree (sharded jax.Arrays included) to ``path``.
    Overwrites an existing checkpoint at the same path."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    return path


def load_params(path: str, like=None):
    """Restore a params pytree. ``like`` (same-structure pytree of arrays
    or ShapeDtypeStructs with shardings) restores directly onto its
    shardings; without it, arrays arrive host-local and callers reshard
    via ``model.shard_params``."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if like is None:
        return ckptr.restore(path)
    target = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding)
        if isinstance(a, jax.Array) else a, like)
    return ckptr.restore(path, target)
