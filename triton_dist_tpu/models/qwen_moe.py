"""Qwen3-MoE decoder under TP/EP.

TPU-native redesign of the reference's ``Qwen3MoELayer`` + ``Qwen3MoE``
(python/triton_dist/models/qwen_moe.py:50-206: dense TP attention + sparse
MoE FFN with softmax-topk routing, HF weight loading). FFN is
``layers.tp_moe.TPMoE`` (AG + grouped ragged-dot GEMMs + ring MoE-RS);
the EP dispatch/combine path (layers/ep_a2a.py) plugs into the same slot
for expert-parallel serving (reference test_ep_moe_inference.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from triton_dist_tpu.layers.common import (
    precompute_rope_cache, rms_norm, shard_param)
from triton_dist_tpu.layers.tp_attn import TPAttn
from triton_dist_tpu.layers.tp_moe import TPMoE
from triton_dist_tpu.models.config import ModelConfig


class Qwen3MoE:
    """TP/EP Qwen3-MoE decoder (reference models/qwen_moe.py:108).

    ``moe_parallel="tp"``: every expert's intermediate dim is sharded
    (TPMoE — AG + grouped GEMM + MoE-RS). ``moe_parallel="ep"``: the
    expert set is sharded, tokens route via the LL all-to-all (EPMoE —
    the reference's EP inference deployment, test_ep_moe_inference.py).
    Attention is TP over the same axis in both."""

    def __init__(self, config: ModelConfig, mesh: Mesh | None = None,
                 axis: str = "tp", fwd_mode: str = "ag_rs",
                 impl: str = "pallas", moe_parallel: str = "tp",
                 sp_axis: str | None = None):
        if mesh is None:
            from triton_dist_tpu.runtime.dist import get_mesh
            mesh = get_mesh()
        assert config.is_moe, "use DenseLLM for dense configs"
        assert moe_parallel in ("tp", "ep")
        self.config = config
        self.mesh, self.axis = mesh, axis
        self.fwd_mode = fwd_mode
        self.moe_parallel = moe_parallel
        self.sp_axis = sp_axis
        if sp_axis is not None:
            # Model-level SP for the MoE decoder (long-context serving):
            # same attention/cache machinery as DenseLLM (forward_sp is
            # REUSED, see below); the FFN hook runs a row-local MoE —
            # every device routes + grouped-FFNs its own S/w tokens with
            # replicated expert weights (no collectives in the FFN).
            assert moe_parallel == "tp" and mesh.shape[axis] == 1, (
                "sp MoE v1: pure-sp grid (tp axis size 1, replicated "
                "expert weights); ep x sp is future work")
            from triton_dist_tpu.ops.flash_decode import (
                create_flash_decode_context)
            from triton_dist_tpu.ops.sp_attention import (
                create_sp_attention_context)
            self.sp_ctx = create_sp_attention_context(
                mesh, sp_axis, causal=True, head_axis=None)
            self.fd_ctx = create_flash_decode_context(mesh, sp_axis)
            self.sp_impl = "ring" if impl == "pallas" else "xla"
            self.fd_impl = impl
        c = config
        self.attn = TPAttn(c.hidden_size, c.num_attention_heads,
                           c.num_key_value_heads, c.head_dim, mesh=mesh,
                           axis=axis, dtype=c.dtype, fwd_mode=fwd_mode,
                           impl=impl, rms_eps=c.rms_norm_eps)
        if moe_parallel == "ep":
            from triton_dist_tpu.layers.ep_moe import EPMoE
            self.moe = EPMoE(c.hidden_size, c.moe_intermediate_size,
                             c.num_experts, c.num_experts_per_tok,
                             mesh=mesh, axis=axis, dtype=c.dtype,
                             impl=impl, norm_topk_prob=c.norm_topk_prob)
        else:
            self.moe = TPMoE(c.hidden_size, c.moe_intermediate_size,
                             c.num_experts, c.num_experts_per_tok,
                             mesh=mesh, axis=axis, dtype=c.dtype,
                             fwd_mode=fwd_mode, impl=impl,
                             norm_topk_prob=c.norm_topk_prob)
        self.rope_cache = precompute_rope_cache(
            c.head_dim, c.max_position_embeddings, c.rope_theta)

    def set_fwd(self, mode: str):
        self.fwd_mode = mode
        self.attn.set_fwd(mode)
        if self.moe_parallel == "tp":
            self.moe.set_fwd("xla" if mode in ("xla", "xla_ar") else "ag_rs")

    # -- params ------------------------------------------------------------
    def init(self, key: jax.Array) -> dict:
        c = self.config
        keys = jax.random.split(key, c.num_hidden_layers + 2)
        layers = []
        for i in range(c.num_hidden_layers):
            ka, km = jax.random.split(keys[i])
            layers.append({
                "attn": self.attn.init(ka),
                "moe": self.moe.init(km),
                "ln_attn": jnp.ones((c.hidden_size,), c.dtype),
                "ln_mlp": jnp.ones((c.hidden_size,), c.dtype),
            })
        embed = (jax.random.normal(keys[-2], (c.vocab_size, c.hidden_size),
                                   c.dtype) * 0.02)
        params = {
            "embed": embed,
            "layers": layers,
            "final_norm": jnp.ones((c.hidden_size,), c.dtype),
            "lm_head": (embed if c.tie_word_embeddings else
                        jax.random.normal(keys[-1],
                                          (c.vocab_size, c.hidden_size),
                                          c.dtype) * 0.02),
        }
        return self.shard_params(params)

    def shard_params(self, params: dict) -> dict:
        m = self.mesh
        out = {
            "embed": shard_param(params["embed"], m, P()),
            "final_norm": shard_param(params["final_norm"], m, P()),
            "lm_head": shard_param(params["lm_head"], m, P()),
            "layers": [],
        }
        for lp in params["layers"]:
            out["layers"].append({
                "attn": self.attn.shard_params(lp["attn"]),
                "moe": self.moe.shard_params(lp["moe"]),
                "ln_attn": shard_param(lp["ln_attn"], m, P()),
                "ln_mlp": shard_param(lp["ln_mlp"], m, P()),
            })
        return out

    # -- forward -----------------------------------------------------------
    def forward(self, params: dict, input_ids: jax.Array, kv_caches,
                offset, mode: str | None = None, kv_start=None,
                block_table=None):
        """Same contract as DenseLLM.forward; MoE FFN needs the
        row-sharded layout (modes xla / ag_rs)."""
        c = self.config
        mode = mode or self.fwd_mode
        if mode == "sp":
            assert kv_start is None, "mode='sp' has no ragged support yet"
            return self.forward_sp(params, input_ids, kv_caches, offset,
                                   block_table=block_table)
        assert block_table is None, "paged caches need mode='sp'"
        if self.moe_parallel == "ep":
            moe_mode = "ep"
            if mode == "ep":
                # Row-sharded attention needs divisible rows; decode-size
                # batches fall back to the replicated gemm_ar path (the
                # reference's EP serving uses the same small-batch mode,
                # test_ep_moe_inference.py).
                w = self.mesh.shape[self.axis]
                attn_mode = "ag_rs" if (input_ids.size % w == 0) else \
                    "gemm_ar"
            else:
                attn_mode = mode
        else:
            moe_mode = "xla" if mode in ("xla", "xla_ar") else "ag_rs"
            attn_mode = mode
        b, s = input_ids.shape
        offset = jnp.asarray(offset, jnp.int32)
        # (B,) per-row offsets supported for S == 1 decode (continuous
        # batching — same contract as DenseLLM.forward).
        off2d = offset[:, None] if offset.ndim else offset
        position_ids = off2d + jnp.tile(
            jnp.arange(s, dtype=jnp.int32)[None], (b, 1))
        if kv_start is not None:
            position_ids = jnp.maximum(
                position_ids - jnp.asarray(kv_start, jnp.int32)[:, None], 0)

        x = params["embed"][input_ids].reshape(b * s, c.hidden_size)
        new_caches = []
        for lp, cache in zip(params["layers"], kv_caches):
            h = rms_norm(x, lp["ln_attn"], c.rms_norm_eps)
            a, cache = self.attn(lp["attn"], h, position_ids,
                                 self.rope_cache, cache, offset,
                                 mode=attn_mode, kv_start=kv_start)
            x = x + a
            h = rms_norm(x, lp["ln_mlp"], c.rms_norm_eps)
            x = x + self.moe(lp["moe"], h, mode=moe_mode)
            new_caches.append(cache)

        x = rms_norm(x, params["final_norm"], c.rms_norm_eps)
        logits = jnp.dot(x.astype(jnp.float32),
                         params["lm_head"].T.astype(jnp.float32))
        return logits.reshape(b, s, c.vocab_size), new_caches

    # -- sequence-parallel forward (REUSED from DenseLLM: the
    # attention/cache/chunk/paged machinery is model-agnostic; only the
    # FFN hook differs) ----------------------------------------------------
    from triton_dist_tpu.models.dense import DenseLLM as _D
    forward_sp = _D.forward_sp
    _paged_scatter = _D._paged_scatter
    del _D

    def _sp_ffn(self, lp, h, constrain, xsh):
        """Row-local MoE FFN on (B, S, H) S-sharded activations:
        route + grouped expert FFN per device on its own tokens,
        replicated expert weights — zero FFN collectives (tokens never
        leave their sequence shard)."""
        from triton_dist_tpu.ops.common import nestable_shard_map
        from triton_dist_tpu.ops.group_gemm import grouped_expert_ffn
        from triton_dist_tpu.ops.moe_utils import topk_reduce, topk_routing
        c = self.config
        k, n_exp = c.num_experts_per_tok, c.num_experts
        mp = lp["moe"]
        sp = self.sp_axis

        def local(hs, rt, wg, wu, wd):
            bb, ss, hh = hs.shape
            rows = hs.reshape(bb * ss, hh)
            logits = rows.astype(jnp.float32) @ rt
            w, idx = topk_routing(logits, k, c.norm_topk_prob)
            pairs = jnp.repeat(rows, k, axis=0)
            out = grouped_expert_ffn(pairs, wg, wu, wd,
                                     idx.reshape(-1), n_exp)
            red = topk_reduce(out.reshape(bb * ss, k, hh), w)
            return red.reshape(hs.shape).astype(hs.dtype)

        spec = P() if h.shape[1] == 1 else P(None, sp, None)
        f = nestable_shard_map(
            local, mesh=self.mesh,
            in_specs=(spec, P(), P(), P(), P()), out_specs=spec,
            check_vma=False)
        return f(h, mp["w_router"], mp["w_gate"], mp["w_up"],
                 mp["w_down"])

    # -- HF weights --------------------------------------------------------
    def load_hf_state_dict(self, state: dict) -> dict:
        """Map a HF Qwen3-MoE state dict to our pytree. Per-expert HF
        weights ``mlp.experts.{e}.{gate,up,down}_proj`` stack into
        (E, in, out) ragged-dot operands."""
        c = self.config

        def get(name):
            a = state[name]
            if hasattr(a, "detach"):
                a = a.detach().cpu().numpy()
            return jnp.asarray(np.asarray(a), c.dtype)

        def lin(name):
            return get(name).T

        layers = []
        for i in range(c.num_hidden_layers):
            p = f"model.layers.{i}."
            experts = {
                "w_gate": jnp.stack([
                    lin(p + f"mlp.experts.{e}.gate_proj.weight")
                    for e in range(c.num_experts)]),
                "w_up": jnp.stack([
                    lin(p + f"mlp.experts.{e}.up_proj.weight")
                    for e in range(c.num_experts)]),
                "w_down": jnp.stack([
                    lin(p + f"mlp.experts.{e}.down_proj.weight")
                    for e in range(c.num_experts)]),
            }
            layers.append({
                "attn": {
                    "w_q": lin(p + "self_attn.q_proj.weight"),
                    "w_k": lin(p + "self_attn.k_proj.weight"),
                    "w_v": lin(p + "self_attn.v_proj.weight"),
                    "w_o": lin(p + "self_attn.o_proj.weight"),
                    "q_norm": get(p + "self_attn.q_norm.weight"),
                    "k_norm": get(p + "self_attn.k_norm.weight"),
                },
                "moe": {
                    "w_router": lin(p + "mlp.gate.weight"
                                    ).astype(jnp.float32),
                    **experts,
                },
                "ln_attn": get(p + "input_layernorm.weight"),
                "ln_mlp": get(p + "post_attention_layernorm.weight"),
            })
        embed = get("model.embed_tokens.weight")
        params = {
            "embed": embed,
            "layers": layers,
            "final_norm": get("model.norm.weight"),
            "lm_head": (embed if c.tie_word_embeddings else
                        get("lm_head.weight")),
        }
        return self.shard_params(params)
