"""ctypes bindings for the native paged-KV allocator (csrc/kvpool).

Same pattern as ``mega.native`` (shared loader:
``runtime.native_lib.load_native``): compile-on-first-use with g++,
fall back to bit-identical Python when no toolchain is available
(tests/test_models.py asserts parity on randomized alloc/free traces).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from triton_dist_tpu.runtime.native_lib import load_native

_SRC = os.path.join(os.path.dirname(__file__), "..", "csrc", "kvpool",
                    "kvpool.cc")
_SO = os.path.join(os.path.dirname(_SRC), "libtdtkv.so")
_LIB = None
_TRIED = False

_I32P = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_U8P = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _configure(lib):
    state = [_I32P, _I32P, _I32P, _U8P]
    lib.tdt_kv_init.restype = ctypes.c_int32
    lib.tdt_kv_init.argtypes = [ctypes.c_int32] * 2 + [_I32P, _I32P]
    for fn in (lib.tdt_kv_alloc_seq, lib.tdt_kv_free_seq):
        fn.restype = ctypes.c_int32
        fn.argtypes = [ctypes.c_int32] * 4 + state + [ctypes.c_int32]
    lib.tdt_kv_alloc_many.restype = ctypes.c_int32
    lib.tdt_kv_alloc_many.argtypes = (
        [ctypes.c_int32] * 4 + state + [_I32P, ctypes.c_int32])


def _load():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        _LIB = load_native(_SRC, _SO, _configure)
    return _LIB


def have_native() -> bool:
    return _load() is not None
