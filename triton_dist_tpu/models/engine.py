"""Inference engine: prefill + jit-compiled decode loop.

TPU-native redesign of the reference ``Engine``
(python/triton_dist/models/engine.py:113-190: prefill with the torch path,
switch layers to the fused mode, capture the decode step in a CUDA graph,
then replay per token). On TPU the CUDA-graph capture is ``jax.jit`` of
the whole decode step (SURVEY.md §7 stage 7: "CUDA graph ≙ jit-compiled
decode step — XLA gives this for free"): one compiled program containing
every layer's fused kernels, replayed per token with no launch overhead.

Backends mirror the reference's (engine.py:116):
``xla_ar`` ≙ torch, ``ag_rs`` ≙ triton_dist, ``gemm_ar`` ≙
triton_dist_gemm_ar (replicated small-batch decode).
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu import obs
from triton_dist_tpu.obs import trace as _trace
from triton_dist_tpu.models.kv_cache import KVCacheManager


def sample_token(logits: jax.Array, key: jax.Array | None = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0) -> jax.Array:
    """Greedy / temperature / top-k / nucleus sampling (reference
    sampling utils, models/utils.py). logits: (B, V) → (B,) int32."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    assert key is not None
    logits = logits / temperature
    if top_k > 0 or top_p < 1.0:
        # ONE descending sort serves both filters (the hot decode step
        # must not pay two O(V log V) passes).
        v = logits.shape[-1]
        s = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k > 0:
            logits = jnp.where(logits < s[:, top_k - 1:top_k], -jnp.inf,
                               logits)
            s = jnp.where(jnp.arange(v)[None, :] < top_k, s, -jnp.inf)
        if top_p < 1.0:
            # Nucleus over the (top-k-filtered) distribution: keep the
            # smallest sorted prefix whose mass reaches top_p. `<=`
            # keeps the top token even at top_p == 0 (degenerates to
            # argmax, not to categorical-over-all--inf ≡ token 0).
            probs = jax.nn.softmax(s, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep = cum - probs <= top_p                 # (B, V) sorted
            kept_min = jnp.min(
                jnp.where(keep, s, jnp.inf), axis=-1)[:, None]
            logits = jnp.where(logits >= kept_min, logits, -jnp.inf)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


#: The auto policy's prior when no measurement exists: the only silicon
#: evidence on record has the mega one-program step 1.49x the plain
#: jitted step (docs/perf.md "First chip contact").
DEFAULT_AUTO_PATH = "mega"


class DecodePathPolicy:
    """``Engine(decode_path="auto")`` arbitration: measured device-step
    gauges pick mega vs plain.

    The devprof pump sampler (obs.devprof, docs/observability.md
    "Device-time truth") labels each profiled pump iteration with the
    decode path that drove it, so parsed captures land in SEPARATE
    ``device.step.mega.*`` / ``device.step.plain.*`` gauges. The
    comparison is PER WINDOW — ``total_ms / windows``, since a
    multi-iteration breach capture unions several step windows into
    one total and a union is not comparable across capture spans. When
    both paths hold a measured per-iteration time, the faster one
    wins; the decision is re-taken per batch (every pump iteration /
    serve call), so the selection tracks the batch shape the captures
    were taken at — silicon numbers arbitrating, the same way
    perfwatch live ratios arbitrate router policy
    (docs/resilience.md). With no measurement (or only one path
    measured) the default is :data:`DEFAULT_AUTO_PATH` — except every
    :data:`PROBE_EVERY`-th decision, which runs the OTHER path so the
    sampler can ever measure it (the perfwatch-probe analog: a policy
    that only runs its prior can never collect the numbers to correct
    it; outputs are bit-identical, so a probe costs only the paths'
    speed difference). Probes are doubly gated on measurability: only
    SAMPLABLE decisions probe (stream-session decode steps under the
    scheduler — ``decide(samplable=True)``; a serve() call resolved
    outside the pump would run its whole generation on the probed
    path with nothing able to capture it), and only while a devprof
    sampler is alive (``obs.devprof.sampler_active()`` — the same
    consumer-gating rationale as ``devprof.arm``). Every decision is
    provenance-counted
    (``engine.decode_path.auto_source.*``) so a dashboard can tell
    measured decisions from prior-based and probe ones.
    ``TDT_MEGA_AUTO=0`` opts out: auto resolves to plain, counted as
    ``env_off``. Either path is greedily bit-identical
    (tests/test_scheduler.py), so the policy is a pure perf choice.
    """

    #: Every Nth decision probes the non-default (or measured-stale)
    #: path — keeps both device.step.* gauges collectable/refreshable.
    PROBE_EVERY = 32

    def __init__(self, enabled: bool | None = None):
        if enabled is None:
            import os
            enabled = os.environ.get("TDT_MEGA_AUTO",
                                     "1").strip() != "0"
        self.enabled = bool(enabled)
        self._n = 0

    @staticmethod
    def measured_step_ms(kind: str) -> float | None:
        """The measured device time of one ``kind`` pump iteration
        (per annotation window) from the last parsed capture, or None
        when never measured (gauges default to 0 — a zero-length
        capture is not a measurement)."""
        total = float(obs.gauge(f"device.step.{kind}.total_ms").value)
        if total <= 0.0:
            return None
        windows = float(obs.gauge(f"device.step.{kind}.windows").value)
        return total / windows if windows > 0 else total

    @staticmethod
    def _can_probe() -> bool:
        """A probe only makes sense where some sampler could capture
        it into the gauges this policy reads."""
        from triton_dist_tpu.obs import devprof
        return devprof.sampler_active()

    def decide(self, samplable: bool = False) -> str:
        """"mega" or "plain" for the next decode step/serve call.

        ``samplable``: this decision drives work a pump sampler could
        actually capture (a StreamSession decode step under the
        scheduler). Only those decisions may probe — a serve() call
        resolved outside the pump would run its WHOLE generation on
        the probed path with no possibility of measurement."""
        if not self.enabled:
            kind, source = "plain", "env_off"
        else:
            self._n += 1
            mega_ms = self.measured_step_ms("mega")
            plain_ms = self.measured_step_ms("plain")
            if mega_ms is not None and plain_ms is not None:
                kind = "mega" if mega_ms <= plain_ms else "plain"
                source = "measured"
            else:
                kind, source = DEFAULT_AUTO_PATH, "default"
            if samplable and self._n % self.PROBE_EVERY == 0 \
                    and self._can_probe():
                # Exploration beat: run the other path this once so a
                # live sampler can (re)measure it — otherwise only the
                # winning path's gauge ever refreshes and the policy
                # can neither correct its prior nor notice staleness.
                kind = "plain" if kind == "mega" else "mega"
                source = "probe"
        obs.counter(f"engine.decode_path.auto_{kind}").inc()
        obs.counter(f"engine.decode_path.auto_source.{source}").inc()
        obs.gauge("serving.mega_selected").set(
            1.0 if kind == "mega" else 0.0)
        return kind


class Engine:
    """Serve loop around a DenseLLM / Qwen3MoE model."""

    def __init__(self, model, batch: int, max_seq: int,
                 prefill_mode: str = "xla_ar", decode_mode: str = "gemm_ar",
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 profile_dir: str | None = None, profile_steps: int = 64,
                 paged: bool = False, page_size: int = 16,
                 prefill_chunk: int | None = None,
                 use_mega: bool = False,
                 decode_path: str | None = None,
                 prefix_cache: bool | None = None,
                 kv_slots_per_dev: int | None = None,
                 slo=None, spec=None):
        self.model = model
        c = model.config
        self.paged = paged
        # Speculative decoding (ISSUE 13, docs/serving.md "Speculative
        # decoding"): a SpecConfig turns stream-session decode into
        # variable-tokens-per-step bursts — a drafter proposes up to k
        # tokens per row, one widened verify step scores them, the
        # accepted prefix commits atomically. Greedy-only: the verify
        # step's acceptance rule IS argmax equality, which is what
        # makes spec-on output bit-identical to spec-off
        # (tests/test_scheduler.py). TDT_SPEC=0 disables at runtime.
        if spec is not None and spec.enabled:
            if temperature > 0.0:
                # ValueError, not assert: user-facing config checks
                # survive ``python -O``.
                raise ValueError(
                    "SpecConfig requires greedy decoding "
                    f"(temperature=0), got temperature={temperature} — "
                    "stochastic speculative sampling needs rejection "
                    "resampling, which this engine does not implement")
            self.spec = spec
        else:
            self.spec = None
        self._spec_step: dict = {}       # verify-window k → jitted step
        # Declarative serving SLO targets (obs.slo.SLOTarget list) the
        # scheduler's SLO tracker evaluates for this engine; None keeps
        # the env-overridable defaults (docs/observability.md "SLOs
        # and burn rates").
        self.slo = slo
        # Cross-request prefix caching (ISSUE 6; paged stream sessions
        # only): full prompt blocks are indexed by token-hash chain and
        # shared across requests, so a warm shared-prefix admission
        # prefills only its suffix. Default on; TDT_PREFIX_CACHE=0 (or
        # prefix_cache=False) opts out — greedy outputs are
        # bit-identical either way (tests/test_scheduler.py).
        if prefix_cache is None:
            import os
            prefix_cache = os.environ.get("TDT_PREFIX_CACHE",
                                          "1").strip() != "0"
        self.prefix_cache = bool(prefix_cache) and paged
        # decode_path: which decode-step program serves this engine.
        # "plain" runs model.forward under jit; "mega" runs the
        # MegaQwen3 fused one-program task-graph step (measured 1.49x
        # the plain jitted step on chip, docs/perf.md "First chip
        # contact"); "auto" arbitrates per batch on the measured
        # device.step.{mega,plain}.total_ms gauges the devprof pump
        # sampler publishes (DecodePathPolicy; TDT_MEGA_AUTO=0 opts
        # out). use_mega=True is the legacy spelling of
        # decode_path="mega". Every engine family serves every path —
        # the mega graph takes per-row kv_start/offset vectors and
        # paged block tables (ISSUE 11), so the old
        # use_mega x (paged|sp|ragged) ValueErrors are gone.
        if decode_path is None:
            decode_path = "mega" if use_mega else "plain"
        elif use_mega and decode_path != "mega":
            # ValueError, not assert: user-facing configuration
            # validation must survive ``python -O`` (ADVICE r5 low).
            raise ValueError(
                f"conflicting config: use_mega=True with "
                f"decode_path={decode_path!r} — pass one or the other")
        if decode_path not in ("plain", "mega", "auto"):
            raise ValueError(
                f"decode_path must be 'plain', 'mega' or 'auto': "
                f"{decode_path!r}")
        self.decode_path = decode_path
        self.use_mega = decode_path == "mega"
        self.decode_policy = (DecodePathPolicy()
                              if decode_path == "auto" else None)
        self._mega = None
        if "sp" in (prefill_mode, decode_mode):
            # Sequence-parallel serving (long context): both phases must
            # share the sequence-sharded cache layout.
            assert prefill_mode == decode_mode == "sp", (
                "mode='sp' applies to prefill and decode together")
            assert getattr(model, "sp_axis", None), (
                "build the model with sp_axis=... for sp serving")
            if paged:
                # vLLM-style paged pools: physical page slots + per-row
                # block tables, admission-controlled per serve() call
                # (models/kv_cache.PagedKVCacheManager + csrc/kvpool).
                from triton_dist_tpu.models.kv_cache import (
                    PagedKVCacheManager)
                world = model.mesh.shape[model.sp_axis]
                assert max_seq % (world * page_size) == 0, (
                    f"max_seq {max_seq} must divide into "
                    f"{world} devices x {page_size}-token pages")
                # kv_slots_per_dev sizes the allocatable pool (default:
                # whole-batch capacity; the sentinel page rides outside
                # it). SMALLER pools are legal — oversubscription
                # streams through block-granular admission; plain
                # serve() still needs whole rows.
                self.kv = PagedKVCacheManager(
                    c.num_hidden_layers, batch, page_size,
                    max_seq // (world * page_size),
                    c.num_key_value_heads, c.head_dim, mesh=model.mesh,
                    axis=model.sp_axis, dtype=c.dtype,
                    slots_per_dev=kv_slots_per_dev)
            else:
                self.kv = KVCacheManager(
                    c.num_hidden_layers, batch, max_seq,
                    c.num_key_value_heads, c.head_dim, mesh=model.mesh,
                    axis=model.sp_axis, dtype=c.dtype, seq_shard=True)
        else:
            assert not paged, "paged serving requires the sp modes"
            self.kv = KVCacheManager(
                c.num_hidden_layers, batch, max_seq, c.num_key_value_heads,
                c.head_dim, mesh=model.mesh, axis=model.axis, dtype=c.dtype)
        self.prefill_mode = prefill_mode
        self.decode_mode = decode_mode
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.key = jax.random.PRNGKey(seed)
        # Decode-loop profile hook (reference engine.py:153-179: a
        # 64-step torch-profiler window inside serve): when set, the
        # first ``profile_steps`` decode steps of each serve() are traced
        # per-host under ``profile_dir``.
        self.profile_dir = profile_dir
        self.profile_steps = profile_steps
        # Chunked sp prefill: bound activation memory on very long
        # prompts by prefilling ``prefill_chunk`` positions at a time
        # (cache-aware ring attention; dense.forward_sp chunked path).
        if prefill_chunk is not None:
            assert prefill_mode == "sp" and not paged, (
                "prefill_chunk applies to the (non-paged) sp engine")
        self.prefill_chunk = prefill_chunk
        self._decode_step: dict = {}        # decode path → jitted step
        self._decode_step_stop: dict = {}
        self._stream_step = None
        self._stream_step_mega = None
        self._admit = None
        self._admit_prefix = None
        self._admit_chunk = None
        self._admit_finish = None

    # -- decode step (jit once = graph capture, engine.py:75-105) ----------
    def _get_mega(self):
        if self._mega is None:
            from triton_dist_tpu.mega import MegaQwen3
            self._mega = MegaQwen3(self.model,
                                   decode_mode=self.decode_mode,
                                   paged=self.paged)
        return self._mega

    def _mega_forward(self, params, caches, token, offset, kv_start,
                      table):
        """The mega one-program step as a decode forward: scalar OR
        per-row ``offset``, ragged ``kv_start``, contiguous or paged
        caches — the same surface the plain forward serves, so the two
        paths interchange under every serving mode (ISSUE 11)."""
        return self._get_mega().step(
            params, token[:, None], caches, offset,
            kv_start=None if self.decode_mode == "sp" else kv_start,
            table=table)

    def resolve_decode_path(self, samplable: bool = False) -> str:
        """The decode path this call runs: the static config, or the
        auto policy's measured-gauge decision — re-taken per call, so
        the selection follows the batch as it changes (docs/serving.md
        "Decode-path selection"). ``samplable`` marks decisions whose
        work a pump sampler could capture (stream-session decode
        steps) — the only ones allowed to probe."""
        if self.decode_path != "auto":
            return self.decode_path
        return self.decode_policy.decide(samplable=samplable)

    def _decode_forward(self, path: str = "plain"):
        """The decode-step forward for one decode path: the mega
        one-program step or model.forward — one place, so the sampling
        and stop bookkeeping below exist once per builder."""
        if path == "mega":
            return self._mega_forward
        model, mode = self.model, self.decode_mode

        def fwd(params, caches, token, offset, kv_start, table):
            return model.forward(
                params, token[:, None], caches, offset, mode=mode,
                kv_start=None if mode == "sp" else kv_start,
                **({"block_table": table} if table is not None else {}))
        return fwd

    def _build_decode_step(self, path: str = "plain"):
        fwd = self._decode_forward(path)

        @jax.jit
        def step(params, caches, token, offset, key, kv_start, table):
            logits, caches = fwd(params, caches, token, offset,
                                 kv_start, table)
            nxt = sample_token(logits[:, -1], key, self.temperature,
                               self.top_k, self.top_p)
            return nxt, caches
        return step

    def _build_decode_step_stop(self, path: str = "plain"):
        """Decode step with in-graph stop bookkeeping: still ONE compiled
        program per token (jit caches per stop-set shape); stopped rows
        keep emitting their stop token."""
        fwd = self._decode_forward(path)

        @jax.jit
        def step(params, caches, token, offset, key, done, stop, kv_start,
                 table):
            logits, caches = fwd(params, caches, token, offset,
                                 kv_start, table)
            nxt = sample_token(logits[:, -1], key, self.temperature,
                               self.top_k, self.top_p)
            nxt = jnp.where(done, token, nxt)
            return nxt, caches, done | jnp.isin(nxt, stop)
        return step

    def serve(self, params, input_ids: jax.Array, gen_len: int,
              stop_tokens=None, kv_start=None) -> jax.Array:
        """Prefill ``input_ids`` (B, S) then generate up to ``gen_len``
        tokens. Returns (B, S + gen_len) (reference ``Engine.serve``
        engine.py:113-190).

        ``stop_tokens``: iterable of token ids ending a row's generation
        (default: the model config's ``eos_token_id`` if set). Rows that
        have stopped keep emitting their stop token (the output stays a
        rectangle — static shapes); the loop exits early once every row
        has stopped.
        """
        if self.spec is not None:
            # Explicit refusal, not a silent ignore (the PR-10 config-
            # check discipline): serve()'s rectangular decode loop has
            # no draft/verify machinery — speculation serves through
            # the stream path (StreamSession / serve_stream / the
            # scheduler), which is where every client route already
            # lands (ModelServer schedules by default).
            raise ValueError(
                "serve() does not run speculative decoding — "
                "SpecConfig engines serve through the stream path "
                "(StreamSession / serve_stream / the scheduler); "
                "build the engine with spec=None for serve()")
        b, s = input_ids.shape
        if gen_len <= 0:
            return input_ids
        # Telemetry (docs/observability.md). ``timed`` gates every
        # clock read and block_until_ready: with the default no-op
        # registry AND tracing off, the serve path pays a handful of
        # no-op calls per CALL (not per token) and the decode loop's
        # span is a shared null context manager. With only tracing on
        # (the flight-recorder posture) the clocks run and the
        # histogram observes land in the no-op registry.
        tel = obs.enabled()
        tr = _trace.enabled()
        timed = tel or tr
        t_serve0 = time.perf_counter() if timed else 0.0
        obs.counter("engine.serve_calls").inc()
        # Resolve the decode path ONCE per serve call (auto re-decides
        # here — per batch); the mega graph serves paged tables and
        # ragged kv_start like the plain forward, so no shape guard.
        path = self.resolve_decode_path()
        obs.counter(f"engine.decode_path.{path}").inc()
        if stop_tokens is None:
            eos = getattr(self.model.config, "eos_token_id", -1)
            stop_tokens = (eos,) if eos >= 0 else ()
        stop_tokens = tuple(stop_tokens)
        has_stop = bool(stop_tokens)
        stop = jnp.asarray(list(stop_tokens) or [-1], jnp.int32)
        kv_start = (jnp.zeros((b,), jnp.int32) if kv_start is None
                    else jnp.asarray(kv_start, jnp.int32))
        self.kv.reset()
        table = None
        if self.paged:
            # Admission control per serve() call: reset the pool (a
            # prior stream session may have left it block-granular),
            # then reserve this batch's whole rows atomically (rollback
            # on exhaustion — csrc/kvpool alloc_many).
            self.kv.reset_pool()
            self.kv.alloc_many(range(b))
            table = self.kv.block_table()
        caches = self.kv.init()

        if self.prefill_mode == "sp":
            # SP serving has no ragged support (forward_sp's contract).
            assert not bool(kv_start.any()), "sp serving is non-ragged"
        t_pre0 = time.perf_counter() if timed else 0.0
        chunk = self.prefill_chunk
        if chunk and self.prefill_mode == "sp" and s > chunk:
            # Cache-aware chunked prefill: activation memory is bounded
            # by the chunk, the cache accumulates the prefix.
            done_pos = 0
            while done_pos < s:
                step_s = min(chunk, s - done_pos)
                logits, caches = self.model.forward(
                    params, input_ids[:, done_pos:done_pos + step_s],
                    caches, done_pos, mode="sp")
                done_pos += step_s
        else:
            logits, caches = self.model.forward(
                params, input_ids, caches, 0, mode=self.prefill_mode,
                kv_start=None if self.prefill_mode == "sp" else kv_start,
                **({"block_table": table} if table is not None else {}))
        self.kv.inc_offset(s)
        token = sample_token(logits[:, -1], self.key, self.temperature,
                             self.top_k, self.top_p)
        if timed:
            # Block so prefill/TTFT measure completed device work, not
            # async dispatch — the observer cost of enabling telemetry.
            jax.block_until_ready(token)
            now = time.perf_counter()
            obs.histogram("engine.prefill_ms").observe(
                (now - t_pre0) * 1e3)
            obs.histogram("engine.ttft_ms").observe(
                (now - t_serve0) * 1e3)
            if tr:
                # Back-dated complete event: the prefill region on the
                # timeline, under the request's bound trace ID.
                _trace.complete(
                    "engine.prefill", "engine",
                    _trace.perf_to_us(t_pre0), (now - t_pre0) * 1e6,
                    args={"batch": b, "prompt_len": s,
                          "chunked": bool(chunk and s > (chunk or 0))})

        if path not in self._decode_step:
            self._decode_step[path] = self._build_decode_step(path)
        decode_step = self._decode_step[path]
        if has_stop and path not in self._decode_step_stop:
            self._decode_step_stop[path] = \
                self._build_decode_step_stop(path)
        decode_step_stop = self._decode_step_stop.get(path)
        # With stop tokens the bookkeeping lives INSIDE the jitted step —
        # still one dispatch per token; without, the plain step runs.
        done = jnp.isin(token, stop) if has_stop else None
        stopped = has_stop and bool(done.all())  # prefill may already stop
        out = [input_ids, token[:, None]]

        def run_steps(n):
            nonlocal token, caches, done, stopped, steps_run
            for i in range(n):
                if stopped:
                    out.append(jnp.broadcast_to(
                        token[:, None], (b, n - i)).astype(token.dtype))
                    return
                with obs.span("engine.decode_step"):
                    self.key, sub = jax.random.split(self.key)
                    off = jnp.int32(self.kv.offset)
                    if has_stop:
                        token, caches, done = decode_step_stop(
                            params, caches, token, off, sub, done, stop,
                            kv_start, table)
                    else:
                        token, caches = decode_step(
                            params, caches, token, off, sub, kv_start,
                            table)
                    if timed:
                        # Block INSIDE the span so the histogram holds
                        # real per-token device latency, not the ~µs
                        # async enqueue — the per-step observer cost of
                        # enabling telemetry (docs/observability.md).
                        jax.block_until_ready(token)
                steps_run += 1
                self.kv.inc_offset(1)
                out.append(token[:, None])
                # the all-done check is a host sync; amortize it
                if has_stop and i % 8 == 7 and bool(done.all()):
                    stopped = True

        n_total = gen_len - 1
        steps_run = 0
        t_dec0 = time.perf_counter() if timed else 0.0
        if self.profile_dir and n_total > 1:
            from triton_dist_tpu.tools.profiler import group_profile
            # One REAL warm-up step before the window: it populates the
            # jit dispatch cache (AOT lower().compile() would not), so
            # the trace shows steady-state per-token replay rather than
            # the one-off XLA compile — and because it goes through the
            # same run_steps path, the RNG stream matches an unprofiled
            # serve() exactly.
            run_steps(1)
            jax.block_until_ready(token)
            n_prof = min(self.profile_steps, n_total - 1)
            with group_profile("engine_decode", self.profile_dir):
                run_steps(n_prof)
                jax.block_until_ready(token)
            run_steps(n_total - 1 - n_prof)
        else:
            run_steps(n_total)
        if timed:
            jax.block_until_ready(token)
            dt = time.perf_counter() - t_dec0
            # Real computed tokens only (first token + executed decode
            # steps) — early-stopped rows' broadcast padding is NOT
            # generation and must not inflate throughput.
            obs.counter("engine.tokens_generated").inc(
                b * (steps_run + 1))
            if steps_run > 0 and dt > 0:
                # Decode-loop throughput (excludes prefill + TTFT,
                # which have their own histograms above).
                obs.gauge("engine.tokens_per_s").set(b * steps_run / dt)
            if tr:
                now = time.perf_counter()
                _trace.complete(
                    "engine.serve", "engine",
                    _trace.perf_to_us(t_serve0),
                    (now - t_serve0) * 1e6,
                    args={"batch": b, "prompt_len": s,
                          "gen_len": gen_len, "steps_run": steps_run,
                          "mega": path == "mega"})
        return jnp.concatenate(out, axis=1)


    # -- continuous batching ----------------------------------------------
    def _build_spec_verify_step(self, k: int):
        """The widened verify step of speculative decoding (ISSUE 13):
        ONE forward scores a k+1-token window per row — the last
        committed token plus k draft tokens — at per-row positions
        ``offsets[b]+[0, k]``, writing their K/V exactly where k+1
        sequential stream steps would and returning the argmax at
        every window position. Compiled once per k (the chunked-
        prefill compile-cache pattern: k buckets are few and small).
        Greedy by construction — acceptance compares these argmaxes
        against the drafts, so emitted tokens are bit-identical to the
        sequential path (models/spec.py). Frozen rows ride along like
        the plain stream step: paged lanes point at the sentinel, and
        contiguous-lane overshoot is dropped or overwritten before any
        mask exposes it."""
        model, mode = self.model, self.decode_mode

        @jax.jit
        def step(params, caches, tokens, offsets, table):
            logits, caches = model.forward(
                params, tokens, caches, offsets, mode=mode,
                **({"block_table": table} if table is not None
                   else {}))
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    caches)
        return step

    def _build_stream_step(self):
        """One decode step with PER-ROW write offsets: each live row
        decodes at its own cache position (frozen rows re-emit their
        token and do not advance). One compiled program per token."""
        model, mode = self.model, self.decode_mode

        @jax.jit
        def step(params, caches, token, offsets, key, done, table):
            logits, caches = model.forward(
                params, token[:, None], caches, offsets, mode=mode,
                **({"block_table": table} if table is not None else {}))
            nxt = sample_token(logits[:, -1], key, self.temperature,
                               self.top_k, self.top_p)
            nxt = jnp.where(done, token, nxt)
            return nxt, caches, jnp.where(done, offsets, offsets + 1)
        return step

    def _build_stream_step_mega(self):
        """The continuous-batching decode step through the mega
        one-program task graph: the per-row offset vector threads into
        the graph's attention position math and per-row KV scatter
        (contiguous lanes or paged table lanes) — same contract and
        same ops as :meth:`_build_stream_step`, so greedy outputs are
        bit-identical (tests/test_scheduler.py) and a session can flip
        between the two steps mid-request (decode_path="auto")."""
        fwd = self._mega_forward

        @jax.jit
        def step(params, caches, token, offsets, key, done, table):
            logits, caches = fwd(params, caches, token, offsets, None,
                                 table)
            nxt = sample_token(logits[:, -1], key, self.temperature,
                               self.top_k, self.top_p)
            nxt = jnp.where(done, token, nxt)
            return nxt, caches, jnp.where(done, offsets, offsets + 1)
        return step

    def _build_admit(self):
        """Admission program: prefill on a batch-1 scratch cache, scatter
        the prefix into row ``row``'s lane at slot 0, emit the first
        token.

        Prompts arrive RIGHT-padded to a power-of-two bucket so jit
        compiles one program per bucket, not per distinct length (a
        public stream of arbitrary lengths must not compile-storm —
        code-review r3g). The pad suffix is causally invisible to the
        first token (sampled at traced position ``length``-1), and its
        scattered K/V slots are overwritten by the row's own decode
        steps before the per-row mask ever exposes them — the same
        argument that makes stale-lane reuse safe."""
        model, mode = self.model, self.prefill_mode

        @jax.jit
        def admit(params, caches, ids, length, row, key):
            lb = ids.shape[1]                       # bucketed length
            small = [(jnp.zeros((1, lb) + ck.shape[2:], ck.dtype),
                      jnp.zeros((1, lb) + cv.shape[2:], cv.dtype))
                     for ck, cv in caches]
            logits, small = model.forward(params, ids, small, 0, mode=mode)
            last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1,
                                                axis=1)[:, 0]
            first = sample_token(last, key, self.temperature, self.top_k, self.top_p)
            new_caches = []
            for (ck, cv), (sk, sv) in zip(caches, small):
                ck = jax.lax.dynamic_update_slice(ck, sk, (row, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(cv, sv, (row, 0, 0, 0))
                new_caches.append((ck, cv))
            return first[0], new_caches
        return admit

    def _build_admit_paged(self):
        """Paged admission: the batch-1 prefill scatters straight into
        the freshly-allocated pages of the admitted row (its
        (w, 1, n_pages) table slice) — no scratch cache, no row copy;
        the pool IS the row's storage (vLLM-style)."""
        model, mode = self.model, self.prefill_mode

        @jax.jit
        def admit(params, pools, ids, length, table_row, key):
            logits, pools = model.forward(params, ids, pools, 0,
                                          mode=mode,
                                          block_table=table_row)
            last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1,
                                                axis=1)[:, 0]
            first = sample_token(last, key, self.temperature, self.top_k, self.top_p)
            return first[0], pools
        return admit

    def _build_admit_paged_prefix(self):
        """Prefix-cache-hit admission: only the prompt SUFFIX runs.

        The suffix's K/V scatter at absolute positions start+[0, S) and
        the attention over the shared cached-prefix blocks both go
        through the paged chunked-prefill path (dense.forward_sp: a
        traced nonzero offset with S > 1). ``start``/``length`` are
        traced, so jit compiles once per padded SUFFIX bucket — the pad
        tail is causally invisible to the real positions and its
        scattered pages sit beyond kv_len until decode overwrites
        them (the standard pad-slot safety argument)."""
        model, mode = self.model, self.prefill_mode

        @jax.jit
        def admit(params, pools, ids, start, length, table_row, key):
            logits, pools = model.forward(params, ids, pools, start,
                                          mode=mode,
                                          block_table=table_row)
            last = jax.lax.dynamic_slice_in_dim(logits, length - 1, 1,
                                                axis=1)[:, 0]
            first = sample_token(last, key, self.temperature,
                                 self.top_k, self.top_p)
            return first[0], pools
        return admit

    def _build_admit_chunk(self):
        """One slice of a CHUNKED admission prefill: forward ``chunk``
        positions into the batch-1 scratch cache at ``offset`` (rope
        and causal mask from the absolute position — the plain
        ``_attention_core`` chunk-at-offset path). Compiled once per
        (chunk, scratch-length) pair; the serving scheduler interleaves
        these between shared decode steps so a long prompt's admission
        never stalls the rows already decoding (docs/serving.md)."""
        model, mode = self.model, self.prefill_mode

        @jax.jit
        def chunk_step(params, small, ids, offset):
            return model.forward(params, ids, small, offset, mode=mode)
        return chunk_step

    def _build_admit_finish(self):
        """Tail of a chunked admission: sample the first token at the
        prompt's true last position inside the final chunk's logits,
        then scatter the scratch prefix into row ``row``'s lane — the
        same pad-slot safety argument as ``_build_admit`` (pad K/V are
        causally invisible and overwritten before any mask exposes
        them)."""

        @jax.jit
        def finish(caches, small, logits, idx, row, key):
            last = jax.lax.dynamic_slice_in_dim(logits, idx, 1,
                                                axis=1)[:, 0]
            first = sample_token(last, key, self.temperature, self.top_k,
                                 self.top_p)
            new_caches = []
            for (ck, cv), (sk, sv) in zip(caches, small):
                ck = jax.lax.dynamic_update_slice(ck, sk, (row, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(cv, sv, (row, 0, 0, 0))
                new_caches.append((ck, cv))
            return first[0], new_caches
        return finish

    @staticmethod
    def _bucket_len(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    def stream_session(self, params) -> "StreamSession":
        """Open an incremental continuous-batching session over this
        engine's decode window (resets the KV cache). The serving
        scheduler drives one of these; ``serve_stream`` is the
        single-caller convenience driver."""
        return StreamSession(self, params)

    def serve_stream(self, params, prompts, gen_len: int,
                     stop_tokens=None) -> list:
        """Continuous batching (beyond the reference; vLLM-style): pump
        a stream of prompts through a fixed ``batch``-row decode window,
        admitting the next prompt into a row the moment its occupant
        finishes — no head-of-line blocking on the longest generation.

        Every row runs at its own cache position: admission resets the
        row's lane (batch-1 prefill scattered to slot 0, rope and mask
        from the per-row offset), so a freed row is reusable
        immediately. Greedy results equal serving each prompt alone
        (tests/test_engine_stream.py). Returns prompt+generated token
        lists in input order.

        Works across all three engine families:
          * dense tp — per-row offsets thread through
            ``_attention_core``'s scatter path; admission scatters a
            scratch prefill into the freed row's private lane;
          * sp (seq-sharded cache) — same, through ``forward_sp``'s
            per-row write/mask/rope path;
          * sp + paged — BLOCK-granular (ISSUE 6): admission maps any
            cached shared-prefix blocks into the row's lanes and
            allocates private blocks for the rest of the prompt, the
            table grows one block at a time as decode crosses page
            boundaries, and retirement returns blocks to the pool
            immediately. Unoccupied rows' lanes point at a per-device
            SENTINEL block, so frozen-row writes are harmless by
            construction; an oversubscribed pool simply admits fewer
            rows at a time instead of refusing to stream
            (docs/serving.md "Block-granular admission").
        """
        obs.counter("engine.serve_stream_calls").inc()
        b = self.kv.batch
        if stop_tokens is None:
            eos = getattr(self.model.config, "eos_token_id", -1)
            stop_tokens = (eos,) if eos >= 0 else ()
        stop_set = set(int(t) for t in stop_tokens)
        if gen_len <= 0:
            return [list(p) for p in prompts]
        n_req = len(prompts)
        assert all(len(p) for p in prompts), "prompts must be non-empty"
        assert all(len(p) + gen_len <= self.kv.max_seq for p in prompts), \
            "prompt + gen_len must fit max_seq"
        if self.paged:
            # Rejecting a never-fitting request up front keeps the
            # admission loop below deadlock-free: a queued head always
            # becomes admissible once enough rows retire.
            bad = [i for i, p in enumerate(prompts)
                   if not self.kv.fits_pool(len(p), gen_len)]
            assert not bad, (
                f"prompts {bad} can never fit the block pool "
                f"({self.kv.slots_per_dev} slots/device)")

        sess = self.stream_session(params)
        row_req = [None] * b                 # request id occupying a row
        row_budget = [0] * b                 # tokens left to generate
        results: list[list[int] | None] = [None] * n_req
        generated: dict[int, list[int]] = {}
        next_req = 0

        def record(r, tok: int):
            """Book one generated token for row r; retire the row when
            its budget is spent or a stop token lands. Returns True if
            the row was freed."""
            nonlocal row_req
            rid = row_req[r]
            generated[rid].append(tok)
            row_budget[r] -= 1
            if row_budget[r] <= 0 or tok in stop_set:
                results[rid] = list(prompts[rid]) + generated.pop(rid)
                row_req[r] = None
                sess.retire_row(r)
                return True
            return False

        def admit_free_rows():
            nonlocal next_req
            for r in range(b):
                if next_req >= n_req:
                    return
                while row_req[r] is None and next_req < n_req:
                    if not sess.can_admit(len(prompts[next_req]),
                                          gen_len):
                        # Not enough blocks yet: FIFO order holds, the
                        # head re-checks after the next retirement.
                        return
                    rid = next_req
                    next_req += 1
                    first = sess.prefill_into_row(r, prompts[rid],
                                                  gen_budget=gen_len)
                    row_req[r] = rid
                    row_budget[r] = gen_len
                    generated[rid] = []
                    # gen_len == 1 or an immediate stop frees the row
                    # again; the inner while then admits the next
                    # request into the same row.
                    record(r, first)

        admit_free_rows()
        while any(rid is not None for rid in row_req):
            # Variable tokens per row per iteration (ISSUE 13): the
            # base paths burst exactly one token, a speculative verify
            # step 1..k+1 — a row retiring mid-burst (stop token /
            # budget) discards the burst's tail, so outputs match the
            # sequential path exactly.
            bursts = sess.decode_burst()
            for r in range(b):
                for tok in bursts.get(r, ()):
                    if row_req[r] is None:
                        break
                    if record(r, int(tok)):
                        break
            admit_free_rows()
        assert all(r is not None for r in results), (
            "stream ended with unserved prompts — admission stalled "
            "with no live rows (block-pool accounting bug)")
        return results

    def serve_ragged(self, params, prompts, gen_len: int,
                     stop_tokens=None, pad_token: int = 0) -> list:
        """Serve prompts of DIFFERENT lengths in one batch.

        Left-pads to a rectangle; the pad prefix is invisible to
        attention (per-row ``kv_start`` mask) and rope positions count
        from each row's first real token — under greedy decoding the
        results match serving each prompt alone (stochastic sampling
        draws differ by batch position). Returns a list of 1-D arrays
        (prompt + generated, pads stripped).
        """
        b = len(prompts)
        lens = [len(p) for p in prompts]
        assert b and all(lens), "serve_ragged needs non-empty prompts"
        s = max(lens)
        ids = np.full((b, s), pad_token, np.int32)
        for i, pr in enumerate(prompts):
            ids[i, s - lens[i]:] = np.asarray(pr, np.int32)
        kv_start = jnp.asarray([s - L for L in lens], jnp.int32)
        out = np.asarray(self.serve(params, jnp.asarray(ids), gen_len,
                                    stop_tokens=stop_tokens,
                                    kv_start=kv_start))
        return [out[i, s - lens[i]:] for i in range(b)]


class StreamSession:
    """Incremental row-level API over an Engine's fixed decode window.

    Owns the mutable continuous-batching state (caches, per-row
    offsets, last tokens, live mask) that ``Engine.serve_stream`` used
    to keep in locals, exposed as the three verbs a scheduler drives:

    * :meth:`prefill_into_row` — admit a prompt into a free row: the
      whole prompt in one admission program, or (``chunk=N``) the
      first N tokens with the rest advanced by :meth:`prefill_step`
      between decode steps, so a long prompt's admission never stalls
      the rows already decoding;
    * :meth:`decode_step` — ONE shared decode step for every live row
      (frozen rows re-emit their token and do not advance);
    * :meth:`retire_row` — free a finished row for the next admission.

    ``Engine.serve_stream`` is a thin single-caller driver over this
    class; the serving scheduler (``serving/scheduler.py``) is another
    — one that feeds rows from MANY client connections into the same
    batch. Exactly one thread may drive a session (the engine state is
    not locked).
    """

    def __init__(self, engine: Engine, params):
        self.engine = engine
        self.params = params
        b = engine.kv.batch
        # sp prefill shards S over the sp axis: buckets must divide.
        # Keyed on EITHER mode being "sp" (init asserts they only come
        # together, but the prefill is what shards S — advisor r3).
        self._sp_world = (
            engine.model.mesh.shape[engine.model.sp_axis]
            if "sp" in (engine.prefill_mode, engine.decode_mode) else 1)
        engine.kv.reset()
        self.cur_table = None
        if engine.paged:
            # Block-granular mode (ISSUE 6): no lane pre-allocation —
            # the pool resets, every row's table lanes point at the
            # per-device sentinel block (so the shared decode step's
            # frozen-row writes are harmless by construction), and
            # admission/decode/retirement move individual blocks. An
            # oversubscribed pool streams fine: it just admits fewer
            # rows at a time (docs/serving.md "Block-granular
            # admission").
            engine.kv.stream_setup(prefix_cache=engine.prefix_cache)
            self.cur_table = engine.kv.block_table()
        self.caches = engine.kv.init()
        if engine._stream_step is None:
            engine._stream_step = engine._build_stream_step()
        if engine._admit is None:
            engine._admit = (engine._build_admit_paged() if engine.paged
                             else engine._build_admit())
        self.token = jnp.zeros((b,), jnp.int32)
        self.offsets = jnp.zeros((b,), jnp.int32)
        self.live = [False] * b
        self._decode_kind: str | None = None  # decided path, unconsumed
        self._host_off = [0] * b     # host shadow of per-row offsets
        self._pending: dict[int, dict] = {}   # row → chunked-prefill state
        # Speculative decoding (ISSUE 13): drafter + per-row budget
        # clamps; decode_burst() runs draft → widened verify → atomic
        # multi-token commit when this is set (docs/serving.md
        # "Speculative decoding").
        self.spec = None
        if engine.spec is not None:
            from triton_dist_tpu.models.spec import SpecState
            self.spec = SpecState(engine.spec, b, engine.kv.max_seq)
        #: Draft/verify wall time of the most recent decode_burst
        #: (None for base-path steps) — the scheduler folds these into
        #: each live request's attribution waterfall (obs.attrib).
        self.last_burst_timing: dict | None = None
        #: Facts about the most recent completed admission (currently
        #: the prefix-cached token count) — the scheduler reads this
        #: right after prefill_into_row/prefill_step returns a first
        #: token, for the request's latency-attribution waterfall
        #: (obs.attrib).
        self.admit_info: dict | None = None

    @property
    def batch(self) -> int:
        return self.engine.kv.batch

    def free_rows(self) -> list:
        """Rows with no occupant (neither live nor mid-prefill)."""
        return [r for r in range(self.batch)
                if not self.live[r] and r not in self._pending]

    def can_admit(self, prompt_len: int, gen_len: int,
                  extra=None) -> bool:
        """Block-granular admission control (paged engines): enough
        free/evictable blocks for this request's worst-case demand,
        net of live rows' commitments and of ``extra`` (an
        accumulated per-device demand for same-batch admissions not
        yet executed). Non-paged sessions always admit."""
        if not self.engine.paged:
            return True
        return self.engine.kv.can_admit(prompt_len, gen_len,
                                        extra=extra)

    def admission_need(self, prompt_len: int, gen_len: int):
        """Per-device worst-case block demand (the ``extra`` operand
        for :meth:`can_admit`); ``None`` for non-paged sessions."""
        if not self.engine.paged:
            return None
        return self.engine.kv.need_per_dev(prompt_len, gen_len)

    # -- admission ---------------------------------------------------------
    def prefill_into_row(self, row: int, prompt, chunk: int | None = None,
                         gen_budget: int | None = None):
        """Admit ``prompt`` into free row ``row``.

        Whole-prompt (``chunk=None``): runs the admission prefill now
        and returns the first sampled token (int). Chunked: runs only
        the first ``chunk``-token slice and returns ``None``; call
        :meth:`prefill_step` (between decode steps) until it returns
        the first token. Chunking applies to the non-paged, non-sp
        scratch-prefill path; other engine families fall back to the
        one-shot admission.

        ``gen_budget`` (paged engines): the tokens this request may
        still generate — the block-granular admission commits that many
        future blocks so a later admission cannot starve this row
        mid-decode. Both shipped drivers (serve_stream, the serving
        scheduler) pass it; omitting it risks a mid-decode pool
        exhaustion on a tight pool.
        """
        assert not self.live[row] and row not in self._pending, \
            f"row {row} is occupied"
        prompt = [int(t) for t in prompt]
        assert prompt, "prompts must be non-empty"
        eng = self.engine
        if (chunk and not eng.paged and eng.prefill_mode != "sp"
                and len(prompt) > chunk
                and -(-len(prompt) // chunk) * chunk <= eng.kv.max_seq):
            return self._start_chunked(row, prompt, int(chunk),
                                       gen_budget=gen_budget)
        return self._admit_whole(row, prompt, gen_budget=gen_budget)

    def _bucket(self, n: int) -> int:
        """Power-of-two prompt bucket rounded up to an sp-world
        multiple (sp prefill shards S over the sp axis)."""
        lb = self.engine._bucket_len(n)
        return -(-lb // self._sp_world) * self._sp_world

    def _admit_whole(self, row: int, prompt: list,
                     gen_budget: int | None = None) -> int:
        eng = self.engine
        eng.key, sub = jax.random.split(eng.key)
        if eng.paged:
            return self._admit_paged(row, prompt, gen_budget, sub)
        lb = min(self._bucket(len(prompt)), eng.kv.max_seq)
        padded = prompt + [0] * (lb - len(prompt))
        ids = jnp.asarray([padded], jnp.int32)
        first, self.caches = eng._admit(
            self.params, self.caches, ids, jnp.int32(len(prompt)),
            jnp.int32(row), sub)
        first = int(first)
        self.admit_info = {"cached": 0}
        self._mark_admitted(row, len(prompt))
        self.token = self.token.at[row].set(first)
        self._spec_start(row, prompt, first, gen_budget)
        return first

    def _admit_paged(self, row: int, prompt: list,
                     gen_budget: int | None, sub) -> int:
        """Block-granular paged admission with cross-request prefix
        reuse: map cached prefix blocks into the row's lanes, then run
        only the SUFFIX through the prefill (the whole prompt when the
        cache misses). Greedy outputs are bit-identical to a cold
        prefill — the cached blocks hold exactly the K/V a cold prefill
        of the same tokens would write."""
        eng, kv = self.engine, self.engine.kv
        L = len(prompt)
        # Size the suffix program against the pool geometry BEFORE
        # claiming hits: the padded suffix bucket scatters at absolute
        # positions cached+[0, lb) and must not run off max_seq. Fewer
        # hits → longer suffix but more room; k=0 (the cold path, lb
        # clamped to max_seq) always fits.
        hashes = kv.prefix_hashes(prompt)
        k = kv.prefix_probe(prompt, hashes=hashes)
        while k > 0:
            if k * kv.page_size + self._bucket(L - k * kv.page_size) \
                    <= kv.max_seq:
                break
            k -= 1
        cached = kv.admit_row(row, prompt,
                              gen_budget=int(gen_budget or 0),
                              use_hits=k, hashes=hashes)
        try:
            # Inside the rollback window: the device upload itself can
            # raise (device OOM), and a failure after admit_row must
            # hand the row's blocks back like any program failure.
            self.cur_table = kv.block_table()
            if cached:
                suffix = prompt[cached:]
                lb = self._bucket(len(suffix))
                ids = jnp.asarray([suffix + [0] * (lb - len(suffix))],
                                  jnp.int32)
                if eng._admit_prefix is None:
                    eng._admit_prefix = eng._build_admit_paged_prefix()
                first, self.caches = eng._admit_prefix(
                    self.params, self.caches, ids, jnp.int32(cached),
                    jnp.int32(len(suffix)),
                    self.cur_table[:, row:row + 1], sub)
            else:
                lb = min(self._bucket(L), kv.max_seq)
                ids = jnp.asarray([prompt + [0] * (lb - L)], jnp.int32)
                first, self.caches = eng._admit(
                    self.params, self.caches, ids, jnp.int32(L),
                    self.cur_table[:, row:row + 1], sub)
            # Materialize HERE: jit returns futures, so an async
            # runtime failure (device OOM, comm error) would otherwise
            # surface past the rollback window below and leave a
            # zombie live row holding its blocks forever.
            first = int(first)
        except Exception:
            # The program never ran to completion: hand the row's
            # blocks straight back (a stranded allocation is a slow
            # production OOM — the quick-tier leak audit's target).
            kv.release_row(row)
            self.cur_table = kv.block_table()
            raise
        kv.register_prefix(row, prompt, hashes=hashes)
        self._note_prefix(row, L, cached)
        self.admit_info = {"cached": cached}
        self._mark_admitted(row, L)
        self.token = self.token.at[row].set(first)
        self._spec_start(row, prompt, first, gen_budget)
        return first

    def _note_prefix(self, row: int, prompt_len: int,
                     cached: int) -> None:
        """Prefix-cache telemetry for one admission
        (docs/observability.md): tokens saved, block-weighted hit
        rate, and a trace instant on the request's timeline."""
        kv = self.engine.kv
        if kv.prefix is None:
            return
        obs.counter("serving.prefill_tokens_saved").inc(cached)
        hits = obs.counter("serving.prefix_hit_blocks")
        hits.inc(cached // kv.page_size)
        lookups = obs.counter("serving.prefix_lookup_blocks")
        lookups.inc(kv.prefix_lookup_blocks(prompt_len))
        # Gauge derived from the cumulative counters, NOT the
        # session-local PrefixCache stats: a pump restart recreates the
        # cache object empty, and the documented contract is the
        # lifetime hit/lookup ratio of the sibling counters.
        if lookups.value > 0:
            obs.gauge("serving.prefix_hit_rate").set(
                round(hits.value / lookups.value, 4))
        if cached:
            _trace.instant("serving.prefix_hit", "serving",
                           args={"row": row, "prompt_len": prompt_len,
                                 "cached_tokens": cached})

    def _start_chunked(self, row: int, prompt: list, chunk: int,
                       gen_budget: int | None = None):
        eng = self.engine
        if eng._admit_chunk is None:
            eng._admit_chunk = eng._build_admit_chunk()
            eng._admit_finish = eng._build_admit_finish()
        n_chunks = -(-len(prompt) // chunk)
        lb = n_chunks * chunk
        padded = prompt + [0] * (lb - len(prompt))
        eng.key, sub = jax.random.split(eng.key)
        self._pending[row] = {
            "ids": np.asarray([padded], np.int32), "len": len(prompt),
            "chunk": chunk, "pos": 0, "key": sub, "budget": gen_budget,
            "small": [(jnp.zeros((1, lb) + ck.shape[2:], ck.dtype),
                       jnp.zeros((1, lb) + cv.shape[2:], cv.dtype))
                      for ck, cv in self.caches]}
        return self.prefill_step(row)

    def prefill_step(self, row: int):
        """Advance row ``row``'s chunked admission by one slice; returns
        the first sampled token (int) once the last slice lands, else
        ``None``."""
        eng = self.engine
        st = self._pending[row]
        c = st["chunk"]
        ids_chunk = jnp.asarray(st["ids"][:, st["pos"]:st["pos"] + c])
        logits, st["small"] = eng._admit_chunk(
            self.params, st["small"], ids_chunk, jnp.int32(st["pos"]))
        st["pos"] += c
        if st["pos"] < st["ids"].shape[1]:
            return None
        del self._pending[row]
        idx = st["len"] - 1 - (st["pos"] - c)   # last real token's index
        first, self.caches = eng._admit_finish(  # in the final chunk
            self.caches, st["small"], logits, jnp.int32(idx),
            jnp.int32(row), st["key"])
        first = int(first)
        self.admit_info = {"cached": 0}
        self._mark_admitted(row, st["len"])
        self.token = self.token.at[row].set(first)
        self._spec_start(row, st["ids"][0, :st["len"]].tolist(), first,
                         st.get("budget"))
        return first

    def cancel_prefill(self, row: int) -> None:
        """Drop a mid-chunk admission (its scratch cache was never
        scattered into the batch, so the session stays consistent)."""
        self._pending.pop(row, None)

    # -- disaggregated handoff (ISSUE 18) ----------------------------------
    def export_row(self, row: int, prompt) -> dict:
        """Extract row ``row``'s finished prompt KV blocks for a
        disaggregated handoff (serving/disagg.py): per-block packed
        payloads plus the dedup-eligible hash chain. Must run while
        the row still holds its blocks — the scheduler invokes the
        request's ``kv_export`` callback just BEFORE ``retire_row``
        (a retired row's private blocks return to the free stack and
        may be overwritten by the next admission)."""
        from triton_dist_tpu.serving import kv_stream
        eng, kv = self.engine, self.engine.kv
        assert eng.paged, "export_row needs a paged engine"
        prompt = [int(t) for t in prompt]
        L = len(prompt)
        n_blocks = kv_stream.block_span(L, kv.page_size)
        hashes = kv.prefix_hashes(prompt) or []
        lookup = kv.prefix_lookup_blocks(L)
        blocks = {}
        for j in range(n_blocks):
            r, lp = kv._block_lane(j)
            idx = (r * kv.phys_slots_per_dev
                   + int(kv._table[r, row, lp]))
            layers = [(np.asarray(pk[idx]), np.asarray(pv[idx]))
                      for pk, pv in self.caches]
            blocks[j] = kv_stream.pack_block(layers)
        return {"hashes": [h.hex() for h in hashes[:lookup]],
                "n_blocks": n_blocks, "blocks": blocks,
                "meta": {"layers": len(self.caches),
                         "page": kv.page_size,
                         "heads": kv.num_kv_heads,
                         "dim": kv.head_dim, "prompt_len": L}}

    def adopt_row(self, row: int, prompt, first: int,
                  gen_budget: int | None, blocks: dict) -> int:
        """Admit row ``row`` DECODE-ONLY from a verified handoff: no
        prefill program runs. The block allocator maps whatever prefix
        the local cache already holds (the dedup the ``kv_need``
        negotiation promised), the SHIPPED payloads are written into
        the privately-allocated remaining blocks, and the row starts
        decoding from the prefill side's first sampled token — under
        greedy decoding the output is bit-identical to a local prefill
        of the same prompt (the shipped blocks hold exactly the K/V a
        local prefill would have written; docs/serving.md
        "Disaggregated prefill/decode"). ``blocks`` maps block index →
        packed payload; a block neither held locally nor shipped fails
        the admission with ``ValueError`` (the caller's re-prefill
        fallback), with full rollback like any failed admission."""
        from triton_dist_tpu.serving import kv_stream
        eng, kv = self.engine, self.engine.kv
        assert eng.paged, "adopt_row needs a paged engine"
        assert not self.live[row] and row not in self._pending, \
            f"row {row} is occupied"
        prompt = [int(t) for t in prompt]
        assert prompt, "prompts must be non-empty"
        L = len(prompt)
        n_blocks = kv_stream.block_span(L, kv.page_size)
        hashes = kv.prefix_hashes(prompt)
        k = kv.prefix_probe(prompt, hashes=hashes)
        cached = kv.admit_row(row, prompt,
                              gen_budget=int(gen_budget or 0),
                              use_hits=k, hashes=hashes)
        try:
            self.cur_table = kv.block_table()
            k_blocks = cached // kv.page_size
            missing = [j for j in range(k_blocks, n_blocks)
                       if j not in blocks]
            if missing:
                raise ValueError(
                    f"adopt_row: blocks {missing} neither held "
                    f"locally nor shipped — incomplete handoff")
            shape = (kv.page_size, kv.num_kv_heads, kv.head_dim)
            caches = self.caches
            for j in range(k_blocks, n_blocks):
                layers = kv_stream.unpack_block(
                    blocks[j], len(caches), shape)
                r, lp = kv._block_lane(j)
                idx = (r * kv.phys_slots_per_dev
                       + int(kv._table[r, row, lp]))
                caches = [
                    (pk.at[idx].set(jnp.asarray(lk, pk.dtype)),
                     pv.at[idx].set(jnp.asarray(lv, pv.dtype)))
                    for (pk, pv), (lk, lv) in zip(caches, layers)]
            if n_blocks > k_blocks:
                # Materialize inside the rollback window, like the
                # admission programs: an async upload failure must not
                # leave a zombie live row holding its blocks.
                jax.block_until_ready(caches[0][0])
            self.caches = caches
        except Exception:
            kv.release_row(row)
            self.cur_table = kv.block_table()
            raise
        kv.register_prefix(row, prompt, hashes=hashes)
        self._note_prefix(row, L, cached)
        self.admit_info = {"cached": cached, "adopted": True}
        self._mark_admitted(row, L)
        self.token = self.token.at[row].set(int(first))
        self._spec_start(row, prompt, int(first), gen_budget)
        return int(first)

    def _mark_admitted(self, row: int, prompt_len: int) -> None:
        obs.counter("engine.stream_admissions").inc()
        _trace.instant("engine.stream_admission", "engine",
                       args={"row": row, "prompt_len": prompt_len})
        self.offsets = self.offsets.at[row].set(prompt_len)
        self._host_off[row] = prompt_len
        self.live[row] = True

    def _spec_start(self, row: int, prompt, first: int,
                    gen_budget) -> None:
        """Seed the drafter for a freshly-admitted row (no-op without
        spec). ``gen_budget`` bounds later bursts; both shipped
        drivers pass it — without it only the max_seq room clamps, so
        a tight paged pool could exhaust mid-burst."""
        if self.spec is not None:
            self.spec.start_row(row, prompt, first, gen_budget)

    # -- decode / retire ---------------------------------------------------
    def decode_kind(self) -> str:
        """The decode path the NEXT :meth:`decode_step` /
        :meth:`decode_burst` will run ("spec"/"mega"/"plain"): "spec"
        when the engine carries a SpecConfig (the burst may still fall
        back to the base path on a 0-draft iteration), otherwise the
        engine's static config or the auto policy's measured-gauge
        decision for the current batch. The scheduler calls this right
        before opening a devprof iteration window so the capture's
        ``device.step.<kind>`` label names the path that actually
        drove it; the decision is cached and consumed by the following
        step. Stream decode steps are samplable work, so these
        decisions may probe."""
        if self.spec is not None:
            self._decode_kind = "spec"
        else:
            self._decode_kind = self.engine.resolve_decode_path(
                samplable=True)
        return self._decode_kind

    def decode_burst(self) -> dict:
        """One shared decode ITERATION with variable tokens per row
        (ISSUE 13): ``{row: [tok, ...]}`` for every live row — exactly
        one token each on the base paths, 1..k+1 on a speculative
        verify step. The scheduler's pump and ``serve_stream`` both
        consume this verb; :meth:`decode_step` remains the
        single-token base-path step."""
        kind = self._decode_kind or self.decode_kind()
        self._decode_kind = None
        self.last_burst_timing = None
        if kind != "spec":
            toks = self._base_step(kind)
            return {r: [int(toks[r])] for r in range(self.batch)
                    if self.live[r]}
        return self._spec_burst()

    def decode_step(self) -> np.ndarray:
        """One shared BASE decode step: every live row decodes at its
        own cache position, frozen rows re-emit their token. Returns
        the (batch,) token vector as numpy.

        Runs the plain stream step or the mega one-program step per
        :meth:`decode_kind` — both are greedily bit-identical, so the
        auto policy may flip paths between steps of one request.
        (Speculative engines burst through :meth:`decode_burst`; this
        verb always runs the base path.)"""
        kind = self._decode_kind
        self._decode_kind = None
        if kind not in ("mega", "plain"):
            kind = self.engine.resolve_decode_path(samplable=True)
        return self._base_step(kind)

    def _base_step(self, kind: str) -> np.ndarray:
        eng = self.engine
        if kind == "mega":
            if eng._stream_step_mega is None:
                eng._stream_step_mega = eng._build_stream_step_mega()
            step_fn = eng._stream_step_mega
        else:
            step_fn = eng._stream_step
        if eng.paged:
            # Incremental block allocation: grow any live row whose
            # NEXT write position crosses into an unallocated page —
            # the admission commitment guarantees the block is there.
            grew = False
            for r in range(len(self.live)):
                if self.live[r]:
                    grew |= eng.kv.ensure_position(r, self._host_off[r])
            if grew:
                self.cur_table = eng.kv.block_table()
        done = jnp.asarray([not alive for alive in self.live])
        with obs.span("engine.stream_step"):
            eng.key, sub = jax.random.split(eng.key)
            self.token, self.caches, self.offsets = step_fn(
                self.params, self.caches, self.token, self.offsets, sub,
                done, self.cur_table)
            if obs.enabled() or _trace.enabled():
                # Real step latency, not the async enqueue (same
                # observer cost as the serve() decode span).
                jax.block_until_ready(self.token)
        for r in range(len(self.live)):
            if self.live[r]:
                self._host_off[r] += 1
        return np.asarray(self.token)

    def _spec_burst(self) -> dict:
        """Draft → widened verify → atomic commit (ISSUE 13).

        The drafter proposes up to k tokens per live row (clamped to
        each row's remaining budget and max_seq room — models/spec.py
        SpecState.plan); ONE widened step scores every window position;
        the longest argmax-matching draft prefix plus the bonus token
        commit per row. Paged pools grow blocks for every position a
        row may KEEP before the step (multi-block ensure_position) and
        rewind the rejected tail after it (rollback_position — blocks
        freed, commitments restored, no leaks: tests/test_block_pool).
        A 0-draft iteration composes with the base paths: the plain/
        mega/auto machinery serves it unchanged."""
        eng = self.engine
        live_rows = [r for r in range(self.batch) if self.live[r]]
        timed = obs.enabled() or _trace.enabled()
        t0 = time.perf_counter() if timed else 0.0
        with obs.span("engine.spec_draft"):
            drafts = self.spec.plan(live_rows, self._host_off)
        t1 = time.perf_counter() if timed else 0.0
        k_step = max((len(d) for d in drafts.values()), default=0)
        if k_step == 0:
            # Nothing to verify: the base path serves this iteration
            # (mega/plain/auto arbitration included) — spec composes
            # with decode-path selection instead of replacing it.
            # samplable=False: the scheduler already labeled this
            # iteration's capture window device.step.spec (decode_kind
            # is "spec" for spec engines), so an auto-policy probe here
            # could never land in the device.step.mega/plain gauges the
            # policy reads — the unmeasurable-probe case the
            # samplable gate exists to prevent.
            obs.counter("serving.spec_fallback_steps").inc()
            kind = eng.resolve_decode_path(samplable=False)
            toks = self._base_step(kind)
            bursts = {r: [int(toks[r])] for r in live_rows}
            for r in live_rows:
                self.spec.observe(r, bursts[r])
            return bursts
        if eng.paged:
            # Cover every position a row may keep BEFORE the step
            # (writes happen in-program; an unallocated position lands
            # on the sentinel and would LOSE an accepted token's K/V).
            # Rows drafted narrower than k_step stay unallocated past
            # their own clamp — their pad writes are sentinel-routed.
            grew = False
            for r in live_rows:
                grew |= eng.kv.ensure_position(
                    r, self._host_off[r] + len(drafts[r]))
            if grew:
                self.cur_table = eng.kv.block_table()
        # Power-of-two k bucket (the admission-bucket pattern): jit
        # compiles one verify program per bucket, not per distinct
        # draft width — pad positions past a row's own drafts are
        # never accepted and their writes are sentinel-routed/dropped.
        k_w = 1
        while k_w < k_step:
            k_w *= 2
        b = self.batch
        toks_in = np.zeros((b, k_w + 1), np.int32)
        toks_in[:, 0] = np.asarray(self.token)
        for r in live_rows:
            d = drafts[r]
            toks_in[r, 1:1 + len(d)] = d
        if k_w not in eng._spec_step:
            eng._spec_step[k_w] = eng._build_spec_verify_step(k_w)
        step_fn = eng._spec_step[k_w]
        with obs.span("engine.spec_verify"):
            nxt, self.caches = step_fn(self.params, self.caches,
                                       jnp.asarray(toks_in),
                                       self.offsets, self.cur_table)
            if timed:
                jax.block_until_ready(nxt)
        nxt = np.asarray(nxt)
        bursts: dict = {}
        n_drafted = n_accepted = n_emitted = 0
        rolled = False
        from triton_dist_tpu.models.spec import accept_greedy
        for r in live_rows:
            a, emitted = accept_greedy(drafts[r], nxt[r])
            if eng.paged and a < len(drafts[r]):
                rolled |= eng.kv.rollback_position(
                    r, self._host_off[r] + a)
            self._host_off[r] += a + 1
            bursts[r] = emitted
            self.spec.observe(r, emitted)
            n_drafted += len(drafts[r])
            n_accepted += a
            n_emitted += len(emitted)
        if rolled:
            self.cur_table = eng.kv.block_table()
        # Commit the device-side state from the host shadows (frozen
        # rows keep their stale offset/token like the base step).
        self.offsets = jnp.asarray(self._host_off, jnp.int32)
        tok_vec = np.asarray(self.token).copy()
        for r in live_rows:
            tok_vec[r] = bursts[r][-1]
        self.token = jnp.asarray(tok_vec)
        self._note_spec(n_drafted, n_accepted, n_emitted)
        if timed:
            t2 = time.perf_counter()
            self.last_burst_timing = {
                "draft_ms": round((t1 - t0) * 1e3, 3),
                "verify_ms": round((t2 - t1) * 1e3, 3)}
        return bursts

    @staticmethod
    def _note_spec(drafted: int, accepted: int, emitted: int) -> None:
        """Speculation telemetry (docs/observability.md): cumulative
        counters plus the two derived gauges the acceptance bar names
        — accept rate (accepted/drafted) and emitted tokens per verify
        step (the tokens/s multiplier speculation buys)."""
        steps = obs.counter("serving.spec_steps")
        steps.inc()
        dc = obs.counter("serving.spec_draft_tokens")
        dc.inc(drafted)
        ac = obs.counter("serving.spec_accepted_tokens")
        ac.inc(accepted)
        ec = obs.counter("serving.spec_emitted_tokens")
        ec.inc(emitted)
        if dc.value > 0:
            obs.gauge("serving.spec_accept_rate").set(
                round(ac.value / dc.value, 4))
        if steps.value > 0:
            obs.gauge("serving.spec_tokens_per_step").set(
                round(ec.value / steps.value, 4))

    def retire_row(self, row: int) -> None:
        """Free a finished row; the next admission may reuse its lane
        immediately. Paged engines release the row's blocks EAGERLY —
        shared prefix blocks drop a reference (refcount-zero indexed
        blocks stay cached, LRU-evictable), private blocks return to
        the free stack, and the row's lanes point back at the sentinel
        so its frozen writes stay harmless."""
        self.live[row] = False
        if self.spec is not None:
            self.spec.retire_row(row)
        if self.engine.paged:
            self.engine.kv.release_row(row)
            self.cur_table = self.engine.kv.block_table()

    def close(self) -> None:
        """Release whatever the session still holds: every live (or
        mid-prefill) row retires, returning its blocks to the pool.
        Rows that already retired released eagerly — a block still
        active for a retired row after close() is a leak the
        quick-tier audit flags (tests/test_scheduler.py)."""
        self._pending.clear()
        for r in range(self.batch):
            if self.live[r]:
                self.retire_row(r)
