"""Sharded training step for the model stack (beyond-reference).

The reference is an inference framework — it has no loss, gradient, or
optimizer path anywhere (SURVEY §2.9: "DP: not a subsystem (inference
framework; torchrun replicates)"). A TPU-native framework gets training
almost for free, because the collective modes the models already expose
(``mode="xla"``: ``lax.all_gather`` + dot + ``lax.psum_scatter``) are
differentiable — XLA derives the backward collectives (AG ↔ RS are each
other's transpose) and inserts the cross-data-parallel gradient psum
from the shardings alone (the scaling-book recipe: annotate, don't
hand-write).

Design:
  * ``make_train_step(model, ...)`` returns a jitted
    ``step(params, opt_state, batch) -> (params, opt_state, metrics)``
    with params/opt_state donated (updates happen in-place in HBM).
  * Next-token objective: ``batch["input_ids"]`` (B, S) predicts its own
    shift; positions where ``batch["loss_mask"]`` is 0 (padding, prompt
    prefixes) are dropped from the mean.
  * Params stay in the model dtype (bf16); the loss/softmax math is
    fp32, and the default optimizer keeps its first moment in fp32
    (``mu_dtype``) so update directions don't quantize to bf16 — the
    usual mixed-precision recipe on TPU.
  * ``remat=True`` checkpoints each decoder layer
    (``DenseLLM.forward(remat=...)``) so activation memory is O(layers)
    smaller at the cost of one extra forward — the HBM/FLOPs trade for
    long-sequence training.

TP comes from the model's own mesh axis; DP needs no code here — shard
the batch over a ``dp`` mesh axis and jit inserts the gradient
all-reduce (tests/test_train.py::test_dp_tp_grid).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as PSpec


def cross_entropy_loss(logits: jax.Array, labels: jax.Array,
                       mask: jax.Array | None = None) -> jax.Array:
    """Mean next-token NLL in fp32.

    logits: (B, S, V); labels: (B, S) int32; mask: (B, S) {0,1} — rows
    of the mean are the mask's nonzeros (all-ones if None).
    """
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if mask is None:
        mask = jnp.ones_like(labels, jnp.float32)
    mask = mask.astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def _fresh_caches(model, batch: int, seq: int, mode: str = "xla"):
    """Zero KV caches sized exactly (B, S) for one training forward.

    Training threads the same cache pytree the inference path uses
    (attention writes k/v at offset 0 then attends causally over them);
    the grads flow through the ``dynamic_update_slice`` write. In
    ``mode="sp"`` the cache is sequence-sharded over the model's
    ``sp_axis`` (matching ``DenseLLM.forward_sp``'s contract).
    """
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    c = model.config
    sp = mode == "sp"
    kv = KVCacheManager(c.num_hidden_layers, batch, seq,
                        c.num_key_value_heads, c.head_dim,
                        mesh=model.mesh,
                        axis=model.sp_axis if sp else model.axis,
                        dtype=c.dtype, seq_shard=sp)
    return kv.init()


def make_train_step(model, optimizer=None, *, mode: str = "xla",
                    remat: bool = False, donate: bool = True):
    """Build the jitted training step.

    Args:
      model: DenseLLM / Qwen3MoE (anything with ``forward(params, ids,
        caches, offset, mode=...)`` returning (B, S, V) logits).
      optimizer: an optax GradientTransformation; default
        ``optax.adamw(3e-4)``.
      mode: forward collective mode. "xla"/"xla_ar" differentiate
        through XLA collectives; "ag_rs"/"gemm_ar" train through the
        fused Pallas kernels — their custom VJPs run the transpose
        fused kernel in the backward — and "ep" (Qwen3MoE with
        moe_parallel="ep") through the Pallas a2a dispatch/combine,
        whose adjoint is the reverse exchange (ops/autodiff.py).
      remat: checkpoint each decoder layer (DenseLLM only).
      donate: donate params/opt_state buffers to the update.

    Returns:
      (step, init_opt_state) where
        step(params, opt_state, batch) -> (params, opt_state, metrics);
        batch = {"input_ids": (B, S) int32, "loss_mask": (B, S)
        optional}; metrics = {"loss": ..., "grad_norm": ...}.
    """
    try:
        import optax
    except ImportError as e:  # optional dep: pip install .[train]
        raise ImportError(
            "models.train needs optax (pip install triton-dist-tpu[train])"
        ) from e
    if optimizer is None:
        optimizer = optax.adamw(3e-4, mu_dtype=jnp.float32)
    if mode not in ("xla", "xla_ar", "ag_rs", "gemm_ar", "ep", "sp"):
        raise ValueError(
            f"training needs a differentiable mode, got {mode!r} "
            "(xla/xla_ar via XLA collectives; ag_rs/gemm_ar/ep via the "
            "fused-kernel VJPs in ops/autodiff.py; sp via ring "
            "attention's native transpose rules)")

    fwd_kwargs = {}
    import inspect
    if "remat" in inspect.signature(model.forward).parameters:
        fwd_kwargs["remat"] = remat
    elif remat:
        raise ValueError(f"{type(model).__name__} has no remat support")

    def loss_fn(params, batch):
        ids = batch["input_ids"]
        b, s = ids.shape
        caches = batch["_caches"]
        # STATIC python 0: under the jit trace jnp.int32(0) is a tracer,
        # which forward_sp's single-shot-prefill guard must reject.
        logits, _ = model.forward(params, ids, caches, 0,
                                  mode=mode, **fwd_kwargs)
        # Predict token i+1 from position i; the last column has no
        # target so it is always dropped.
        labels = jnp.concatenate(
            [ids[:, 1:], jnp.zeros((b, 1), ids.dtype)], axis=1)
        mask = batch.get("loss_mask")
        mask = (jnp.ones((b, s), jnp.float32) if mask is None
                else mask.astype(jnp.float32))
        mask = mask.at[:, -1].set(0.0)
        return cross_entropy_loss(logits, labels, mask)

    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        gn = optax.global_norm(grads)
        return params, opt_state, {"loss": loss, "grad_norm": gn}

    jit_step = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    cache_by_shape: dict = {}

    def run_step(params, opt_state, batch):
        batch = dict(batch)
        ids = batch["input_ids"]
        # Zero caches built OUTSIDE jit so their sharding comes from
        # KVCacheManager (head-sharded over tp); they are read-only
        # inputs (the step discards new_caches), so one allocation per
        # (B, S) shape is reused across the whole training run.
        if ids.shape not in cache_by_shape:
            cache_by_shape[ids.shape] = _fresh_caches(model, *ids.shape,
                                                      mode=mode)
        batch["_caches"] = cache_by_shape[ids.shape]
        return jit_step(params, opt_state, batch)

    def init_opt_state(params):
        state = optimizer.init(params)
        # Moments inherit the params' mesh shardings via zeros_like, but
        # optimizer SCALARS (e.g. adam's count) land on the default
        # device as single-device arrays. Pin them to a replicated mesh
        # sharding so (a) one jit sees a consistent device set and (b) a
        # checkpoint restore using this state as ``like`` round-trips
        # onto the mesh instead of committing to device 0.
        rep = NamedSharding(model.mesh, PSpec())
        return jax.tree.map(
            lambda a: (jax.device_put(a, rep)
                       if isinstance(a, jax.Array)
                       and not isinstance(a, jax.core.Tracer)
                       and not isinstance(a.sharding, NamedSharding)
                       else a), state)

    return run_step, init_opt_state
