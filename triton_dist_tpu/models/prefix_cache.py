"""Cross-request prefix cache index for the paged KV pool (ISSUE 6).

vLLM-style radix/prefix caching flattened onto the block-hash chain:
logical block ``j`` of a prompt is identified by

    h_j = sha1(h_{j-1} || tokens[j*page : (j+1)*page])

so two prompts share block ``j`` iff their first ``(j+1)*page`` tokens
are identical — the radix-tree lookup degenerates to walking the hash
chain until the first miss. Only FULL blocks are ever indexed: the
tail (partial) block of a sequence is written during decode and must
stay private, which is what makes copy-on-write degenerate to
"write-blocks-are-private-by-construction" — an indexed block is
immutable for its whole life in the pool (docs/serving.md
"Prefix cache").

This class is the pure host-side INDEX: hash → (device, slot),
slot → hash, and a per-device LRU of *evictable* slots (refcount has
dropped to zero in the allocator, data still resident). The refcounts
themselves — and the free stacks the evicted slots return to — live in
``PagedKVCacheManager``, which owns every state transition:

    free ──alloc──▶ active(ref=1) ──register──▶ active+indexed
      ▲                │  ▲                        │
      └────deref───────┘  └────────claim───────────┤ deref→0
                                                   ▼
                                         evictable (LRU) ──evict──▶ free

Thread-safety: none — exactly one thread drives a stream session
(models/engine.py contract), and the manager calls in from that
thread only.
"""

from __future__ import annotations

import collections
import hashlib


class PrefixCache:
    """Block-hash index + per-device LRU for refcount-zero blocks."""

    def __init__(self, world: int, page_size: int):
        self.world = world
        self.page_size = page_size
        self._map: dict[bytes, tuple[int, int]] = {}    # hash → (r, slot)
        self._by_slot: dict[tuple[int, int], bytes] = {}
        # slot → None, insertion-ordered: front = least recently used.
        self._evictable: list = [collections.OrderedDict()
                                 for _ in range(world)]
        # Block-weighted stats, cumulative over THIS cache object's
        # lifetime (stats()/report.py; the serving.prefix_hit_rate
        # gauge uses the process-global obs counters instead, which
        # survive session restarts).
        self.lookup_blocks = 0
        self.hit_blocks = 0
        self.evictions = 0

    # -- hashing -----------------------------------------------------------
    def block_hashes(self, tokens) -> list[bytes]:
        """Hash chain over the FULL blocks of ``tokens`` (the partial
        tail block, if any, is not hashable — it is still mutable)."""
        page = self.page_size
        out: list[bytes] = []
        h = b""
        for j in range(len(tokens) // page):
            blk = tokens[j * page:(j + 1) * page]
            m = hashlib.sha1(h)
            m.update(b",".join(str(int(t)).encode() for t in blk))
            h = m.digest()
            out.append(h)
        return out

    # -- lookup ------------------------------------------------------------
    def probe(self, hashes) -> int:
        """Longest indexed prefix of ``hashes`` (STATELESS — no
        counters, no LRU touch): the admission planner uses this to
        size the suffix program before committing to the hits."""
        k = 0
        for h in hashes:
            if h not in self._map:
                break
            k += 1
        return k

    def chain_prefix_match(self, hashes) -> int:
        """Longest locally-held hash-chain prefix of ``hashes`` — the
        ``kv_need`` primitive of the disaggregated handoff
        (docs/serving.md "Disaggregated prefill/decode"): a decode
        replica answers a ``kv_offer`` with this count, so the prefill
        side ships ONLY the missing suffix. Identical walk to
        :meth:`probe` (stateless, no LRU touch), exposed under the
        protocol's name so the negotiation and the admission planner
        provably share one lookup."""
        return self.probe(hashes)

    def resolve(self, hashes, max_hits: int | None = None):
        """Resolve the longest indexed prefix to its slots (no counter
        accounting — the allocator accounts only admissions that
        succeed, so a rolled-back admission cannot skew the hit rate).
        Returns ``[(r, slot), ...]`` for the first ``k`` blocks
        (``k <= max_hits`` when given)."""
        k = self.probe(hashes)
        if max_hits is not None:
            k = min(k, max_hits)
        return [self._map[h] for h in hashes[:k]]

    def account(self, lookup_blocks: int, hit_blocks: int) -> None:
        """Fold one successful admission into the cumulative
        block-weighted hit/lookup counters."""
        self.lookup_blocks += lookup_blocks
        self.hit_blocks += hit_blocks

    def lookup(self, hashes, max_hits: int | None = None):
        """``resolve`` + ``account`` in one step, for callers without a
        rollback path."""
        hits = self.resolve(hashes, max_hits=max_hits)
        self.account(len(hashes), len(hits))
        return hits

    def hit_rate(self) -> float:
        """Cumulative block-weighted hit rate in [0, 1]."""
        return (self.hit_blocks / self.lookup_blocks
                if self.lookup_blocks else 0.0)

    # -- index maintenance (driven by the allocator) -----------------------
    def register(self, h: bytes, r: int, slot: int) -> bool:
        """Index a freshly-computed full block. First writer wins: a
        hash already indexed (or a slot already carrying another hash)
        leaves the existing entry — the duplicate block stays private
        and is freed normally at retire."""
        if h in self._map or (r, slot) in self._by_slot:
            return False
        self._map[h] = (r, slot)
        self._by_slot[(r, slot)] = h
        return True

    def is_indexed(self, r: int, slot: int) -> bool:
        return (r, slot) in self._by_slot

    def claim(self, r: int, slot: int) -> None:
        """An indexed block is being re-shared (refcount 0 → 1): pull
        it out of the evictable LRU; the index entry stays."""
        self._evictable[r].pop(slot, None)

    def release(self, r: int, slot: int) -> None:
        """An indexed block's refcount dropped to zero: its data stays
        resident and reusable, but it becomes the eviction candidate
        pool's most-recently-used entry."""
        self._evictable[r].pop(slot, None)
        self._evictable[r][slot] = None

    def evictable_count(self, r: int) -> int:
        return len(self._evictable[r])

    def evict_lru(self, r: int) -> int | None:
        """Drop device ``r``'s least-recently-used refcount-zero block
        from the index and hand its slot to the allocator. ``None``
        when nothing is evictable."""
        if not self._evictable[r]:
            return None
        slot, _ = self._evictable[r].popitem(last=False)
        h = self._by_slot.pop((r, slot))
        del self._map[h]
        self.evictions += 1
        return slot

    # -- introspection -----------------------------------------------------
    def stats(self) -> dict:
        return {"indexed_blocks": len(self._map),
                "evictable_blocks": sum(len(e) for e in self._evictable),
                "lookup_blocks": self.lookup_blocks,
                "hit_blocks": self.hit_blocks,
                "hit_rate": round(self.hit_rate(), 4),
                "evictions": self.evictions}
