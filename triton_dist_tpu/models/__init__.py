"""Models + engine (reference L7: python/triton_dist/models/).

``AutoLLM.from_pretrained`` (reference models/__init__.py:33) dispatches
on the HF config's ``model_type``/MoE fields to ``DenseLLM`` or
``Qwen3MoE`` and loads safetensors weights when present.
"""

from __future__ import annotations

import glob
import os

from triton_dist_tpu.models.config import ModelConfig
from triton_dist_tpu.models.dense import DenseLLM
from triton_dist_tpu.models.qwen_moe import Qwen3MoE
from triton_dist_tpu.models.kv_cache import KVCacheManager
from triton_dist_tpu.models.engine import Engine, StreamSession, sample_token
from triton_dist_tpu.models.spec import SpecConfig
from triton_dist_tpu.models.train import make_train_step, cross_entropy_loss
from triton_dist_tpu.models import presets

__all__ = ["ModelConfig", "DenseLLM", "Qwen3MoE", "KVCacheManager",
           "Engine", "StreamSession", "sample_token", "AutoLLM", "make_train_step", "presets",
           "cross_entropy_loss", "SpecConfig"]


def _load_safetensors_state(model_dir: str) -> dict:
    """Read all ``*.safetensors`` shards into one name→array dict
    (the reference loads via HF from_pretrained; we read directly —
    no torch needed on the load path)."""
    from safetensors import safe_open  # ships with transformers

    state = {}
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors under {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                state[name] = f.get_tensor(name)
    return state


class AutoLLM:
    """Dispatching loader (reference ``AutoLLM.from_pretrained``,
    models/__init__.py:33-64)."""

    @staticmethod
    def build(config: ModelConfig, mesh=None, axis: str = "tp",
              fwd_mode: str = "ag_rs", impl: str = "pallas"):
        cls = Qwen3MoE if config.is_moe else DenseLLM
        return cls(config, mesh=mesh, axis=axis, fwd_mode=fwd_mode,
                   impl=impl)

    @staticmethod
    def from_pretrained(model_dir: str, mesh=None, axis: str = "tp",
                        fwd_mode: str = "ag_rs", impl: str = "pallas"):
        """Build the model from a local HF checkpoint dir and load + shard
        its weights. Returns (model, params)."""
        config = ModelConfig.from_hf_config(model_dir)
        model = AutoLLM.build(config, mesh=mesh, axis=axis,
                              fwd_mode=fwd_mode, impl=impl)
        state = _load_safetensors_state(model_dir)
        params = model.load_hf_state_dict(state)
        return model, params
