"""Model configuration (reference ``ModelConfig``,
python/triton_dist/models/config.py — extended with the MoE fields the
reference keeps on the HF config object, models/qwen_moe.py:108-140)."""

from __future__ import annotations

import dataclasses
import json
import os

import jax.numpy as jnp


def _scalar_eos(v) -> int:
    """HF configs store eos_token_id as an int or a list; keep the first
    (generation stops on it; multi-eos callers pass stop_tokens to
    ``Engine.serve``)."""
    if v is None:  # "eos_token_id": null is valid HF JSON
        return -1
    if isinstance(v, (list, tuple)):
        return int(v[0]) if v else -1
    return int(v)


@dataclasses.dataclass
class ModelConfig:
    """Architecture hyperparameters for Qwen3-class decoders."""

    hidden_size: int = 1024
    intermediate_size: int = 3072
    num_hidden_layers: int = 4
    num_attention_heads: int = 16
    num_key_value_heads: int = 8
    head_dim: int = 64
    vocab_size: int = 32000
    max_position_embeddings: int = 4096
    rope_theta: float = 1_000_000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    dtype: object = jnp.bfloat16
    # MoE (0 experts = dense; reference Qwen3MoE fields)
    num_experts: int = 0
    num_experts_per_tok: int = 0
    moe_intermediate_size: int = 0
    norm_topk_prob: bool = True
    model_type: str = "qwen3"
    # Qwen3 applies RMSNorm to q/k heads; Llama-3 / Seed-OSS-class dense
    # models (reference AutoLLM maps both to DenseLLM,
    # models/__init__.py:33-42) do not.
    qk_norm: bool = True
    eos_token_id: int = -1  # -1 = no stop token

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    def param_split(self) -> tuple[int, int, int]:
        """(attn params/layer, mlp params/layer incl. all experts,
        embedding params) — the ONE accounting shared by
        ``models.presets.param_count`` and
        ``parallel.plan_parallelism`` (review r5f-1: two hand-rolled
        copies had already diverged on tied embeddings). Norm weights
        are omitted (<0.1%)."""
        h = self.hidden_size
        attn = h * self.head_dim * (2 * self.num_attention_heads
                                    + 2 * self.num_key_value_heads)
        if self.is_moe:
            mlp = 3 * h * self.moe_intermediate_size * self.num_experts
        else:
            mlp = 3 * h * self.intermediate_size
        embed = (1 if self.tie_word_embeddings else 2) * h * self.vocab_size
        return attn, mlp, embed

    @classmethod
    def from_hf_config(cls, path_or_dict) -> "ModelConfig":
        """Build from a HF ``config.json`` (file path, model dir, or dict) —
        the reference reads the same fields off AutoConfig
        (models/dense.py:117-150)."""
        if isinstance(path_or_dict, dict):
            cfg = path_or_dict
        else:
            p = path_or_dict
            if os.path.isdir(p):
                p = os.path.join(p, "config.json")
            with open(p) as f:
                cfg = json.load(f)
        return cls(
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg.get("intermediate_size", 0),
            num_hidden_layers=cfg["num_hidden_layers"],
            num_attention_heads=cfg["num_attention_heads"],
            num_key_value_heads=cfg.get("num_key_value_heads",
                                        cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim",
                             cfg["hidden_size"] // cfg["num_attention_heads"]),
            vocab_size=cfg["vocab_size"],
            max_position_embeddings=cfg.get("max_position_embeddings", 4096),
            rope_theta=cfg.get("rope_theta", 1e6),
            rms_norm_eps=cfg.get("rms_norm_eps", 1e-6),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            num_experts=cfg.get("num_experts", 0),
            num_experts_per_tok=cfg.get("num_experts_per_tok", 0),
            moe_intermediate_size=cfg.get("moe_intermediate_size", 0),
            norm_topk_prob=cfg.get("norm_topk_prob", True),
            model_type=cfg.get("model_type", "qwen3"),
            qk_norm=cfg.get("model_type", "qwen3").startswith("qwen3"),
            eos_token_id=_scalar_eos(cfg.get("eos_token_id", -1)),
        )
