"""Static model checker for the EP all-to-all's slab/chunk protocol.

``ops/all_to_all.py``'s ``_a2a_kernel`` is the repo's port of the
reference's headline low-latency AllToAll (137µs vs DeepEP's 182µs,
SURVEY §6) — the kernel SURVEY's "hard parts" calls the riskiest port
because the reference's safety argument is a **call-count-parity
double-buffer protocol** (low_latency_all_to_all.py:140-143: symmetric
buffers persist across calls, so call ``k`` and call ``k+1`` must land
in different buffer/signal slots). The TPU re-expression collapses
that protocol (all_to_all.py:25-28: each ``pallas_call`` owns its
buffers and its DMA semaphores start and finish at zero) — a design
decision this checker turns from a docstring claim into a proof
obligation.

Single-call model (:func:`a2a_trace`): per rank, the slab/chunk push is
mirrored into protocol events by executing the kernel's OWN schedule
helpers (``a2a_send_peer`` / ``a2a_wait_src`` / ``a2a_live_chunks``)
with concrete ranks — per-(slab, chunk) semaphore slots, live-chunk
gating from a counts matrix, the full-mesh entry barrier, and the
send-side drain. Verified per counts pattern (full, ragged,
all-zero, one-hot) for worlds 1..8: every live chunk lands exactly
once with a prior wait (``a2a.race`` / ``a2a.coverage``), every
semaphore balances on both sides (``a2a.signal_wait_imbalance``), and
the greedy maximal execution completes (``a2a.deadlock``). The
fp8 path's scale side channel — the analog of the reference's
separate ``putmem_signal`` scale channel — carries its own signal
accounting (``fp8_sideband=True``).

Cross-call composition (:func:`a2a_call_sequence`): consecutive calls
concatenate per rank (``protocol_model.concat_traces``) with semaphore
slots assigned by the buffering regime —

- ``"fresh"`` — slot = call index: the TPU collapse case. Fresh
  per-``pallas_call`` semaphores mean no slot is ever reused, so
  proving each call's protocol plus slot disjointness proves the
  sequence.
- ``"parity"`` — slot = call % 2: the reference's ``call_count``
  parity re-expression. Slot reuse at distance 2 is legal only
  because every call fully drains (send-side waits) before its rank
  proceeds, which the composed balance/deadlock verdicts check.

Both regimes are verified for sequences of length 1..4, and a
structural invariant — every event's slot equals its call's expected
slot — is checked independently of execution
(:func:`check_call_parity`, code ``a2a.call_parity``): the
swapped-parity mutant (a call signalling the other buffer's slots,
the classic double-buffer bug) is caught even where the counting
verdicts alone would merely deadlock.

Model scope: slot assignment, signal/wait accounting and ordering.
Cross-call write-after-read hazards on *persistent* symmetric buffers
are exactly what the TPU design removes (fresh buffers per call);
for the parity regime the model checks slot discipline, not HBM
buffer lifetimes — docs/analysis.md "What the protocol models check —
and what they can't".
"""

from __future__ import annotations

import dataclasses
import functools

from triton_dist_tpu.analysis.protocol_model import (
    Ev, Trace, Violation, anchor_of, barrier_evs, check_trace,
    concat_traces, copy_trace, violations_to_findings)

__all__ = [
    "a2a_trace", "a2a_call_sequence", "check_call_parity",
    "counts_patterns", "verify_a2a", "swap_call_parity",
]

#: Headline-ish model shape: the reference's LL config is 128
#: tokens/rank; chunk 32 exercises multi-chunk slabs (the fp8 wire's
#: 1-byte alignment class) without blowing up trace sizes.
CAPACITY = 128
CHUNK = 32


@functools.lru_cache(maxsize=None)
def _live(count: int, chunk: int) -> int:
    """Live chunks for one slab — executes the kernel's own
    ``a2a_live_chunks`` with concrete values."""
    from triton_dist_tpu.ops.all_to_all import a2a_live_chunks
    return int(a2a_live_chunks(count, chunk))


@functools.lru_cache(maxsize=None)
def _send_order(me: int, world: int) -> tuple:
    from triton_dist_tpu.ops.all_to_all import a2a_send_peer
    return tuple(int(a2a_send_peer(me, i, world))
                 for i in range(1, world))


@functools.lru_cache(maxsize=None)
def _wait_order(me: int, world: int) -> tuple:
    from triton_dist_tpu.ops.all_to_all import a2a_wait_src
    return tuple(int(a2a_wait_src(me, i, world))
                 for i in range(1, world))


def counts_patterns(world: int, capacity: int = CAPACITY,
                    chunk: int = CHUNK) -> dict:
    """Representative send-count matrices (counts[src][dst] = live
    rows src→dst): full slabs, ragged counts spanning 0..capacity
    with chunk-unaligned values, all-zero (a2a of an empty batch), and
    one-hot routing (every token to one expert rank)."""
    full = [[capacity] * world for _ in range(world)]
    ragged = [[((3 * s + 5 * d + 1) * (chunk + 3)) % (capacity + 1)
               for d in range(world)] for s in range(world)]
    zero = [[0] * world for _ in range(world)]
    onehot = [[capacity if d == (s + 1) % world else 0
               for d in range(world)] for s in range(world)]
    return {"full": full, "ragged": ragged, "zero": zero,
            "onehot": onehot}


def a2a_trace(world: int, counts, chunk: int = CHUNK, call: int = None,
              slot: int = 0, fp8_sideband: bool = False,
              name: str = None) -> Trace:
    """Event trace of one ``_a2a_kernel`` dispatch.

    Mirrors the kernel phase-for-phase: self-slab VMEM copy (no DMA),
    entry ``barrier_all``, live-chunk push in ``a2a_send_peer`` order
    with per-(slab, chunk) semaphore slots, ``a2a_wait_src``-ordered
    arrival waits, send-side drain; recv-slab consumption (the
    caller's read of the kernel output) is guarded per chunk by its
    delivery semaphore. ``slot`` namespaces the semaphores for
    cross-call composition; ``fp8_sideband`` adds the scale channel's
    one-message-per-pair signal accounting."""
    epoch = 0 if call is None else call
    events: dict = {}
    expected: dict = {}
    for me in range(world):
        ev: list = []
        if world > 1:
            ev.extend(barrier_evs(me, world, ("a2a", epoch)))
            for p in _send_order(me, world):
                for c in range(_live(counts[me][p], chunk)):
                    ev.append(Ev("signal", me,
                                 sem=("a2a", me, p, c, slot),
                                 dst=p, call=call))
                if fp8_sideband:
                    ev.append(Ev("signal", me,
                                 sem=("scale", me, p, slot),
                                 dst=p, call=call))
            for j in _wait_order(me, world):
                for c in range(_live(counts[j][me], chunk)):
                    ev.append(Ev("wait_recv", me,
                                 sem=("a2a", j, me, c, slot),
                                 call=call))
                if fp8_sideband:
                    ev.append(Ev("wait_recv", me,
                                 sem=("scale", j, me, slot),
                                 call=call))
            for p in _send_order(me, world):
                for c in range(_live(counts[me][p], chunk)):
                    ev.append(Ev("wait_send", me,
                                 sem=("a2a", me, p, c, slot),
                                 call=call))
                if fp8_sideband:
                    ev.append(Ev("wait_send", me,
                                 sem=("scale", me, p, slot),
                                 call=call))
        # Kernel-exit handoff: the caller reads every live recv chunk.
        want: dict = {}
        for j in range(world):
            for c in range(_live(counts[j][me], chunk)):
                guard = None if j == me else ("a2a", j, me, c, slot)
                ev.append(Ev("consume", me,
                             key=("slab", epoch, j, c), guard=guard,
                             call=call))
                want[("slab", epoch, j, c)] = 1
            if fp8_sideband and j != me:
                ev.append(Ev("consume", me, key=("scales", epoch, j),
                             guard=("scale", j, me, slot), call=call))
                want[("scales", epoch, j)] = 1
        events[me] = ev
        expected[me] = want
    from triton_dist_tpu.ops import all_to_all
    return Trace(
        name=name or f"a2a[w{world} c{chunk}"
                     f"{' fp8' if fp8_sideband else ''}]",
        world=world, dirs=1, events=events, expected=expected,
        anchor=anchor_of(all_to_all._a2a_kernel), code_prefix="a2a")


def a2a_call_sequence(world: int, n_calls: int, counts_seq=None,
                      buffering: str = "fresh", chunk: int = CHUNK,
                      fp8_sideband: bool = False) -> Trace:
    """Composed trace of ``n_calls`` consecutive dispatches under one
    buffering regime: ``"fresh"`` (slot = call — the TPU per-
    ``pallas_call`` collapse, all_to_all.py:25-28) or ``"parity"``
    (slot = call % 2 — the reference's call_count re-expression)."""
    if buffering not in ("fresh", "parity"):
        raise ValueError(f"unknown buffering {buffering!r}")
    if counts_seq is None:
        pats = list(counts_patterns(world, chunk=chunk).values())
        counts_seq = [pats[k % len(pats)] for k in range(n_calls)]
    assert len(counts_seq) == n_calls
    traces = []
    for k in range(n_calls):
        slot = k if buffering == "fresh" else k % 2
        traces.append(a2a_trace(world, counts_seq[k], chunk=chunk,
                                call=k, slot=slot,
                                fp8_sideband=fp8_sideband))
    return concat_traces(
        traces, f"a2a_seq[w{world} x{n_calls} {buffering}"
                f"{' fp8' if fp8_sideband else ''}]")


def check_call_parity(trace: Trace, buffering: str = "parity") -> list:
    """Structural double-buffer invariant, independent of execution:
    every call-stamped a2a/scale semaphore event must use its call's
    slot — ``call % 2`` under the parity regime, ``call`` under fresh
    per-call buffers. A call signalling the *other* buffer's slots is
    the classic double-buffer bug (the reference guards it with
    ``signal_wait_until(EQ, call_count)``); here it is a distinct
    finding class, ``a2a.call_parity``."""
    v = []
    for r, evs in trace.events.items():
        for e in evs:
            if e.call is None or e.sem is None or \
                    e.sem[0] not in ("a2a", "scale"):
                continue
            slot = e.sem[-1]
            want = e.call % 2 if buffering == "parity" else e.call
            if slot != want:
                v.append(Violation(
                    "a2a.call_parity",
                    f"{trace.name}: rank {r} {e.kind} on sem {e.sem} "
                    f"uses buffer slot {slot} at call {e.call} "
                    f"(expected slot {want} under {buffering} "
                    f"buffering) — double-buffer parity violated"))
    return v


def verify_a2a(worlds=range(1, 9), seq_lens=(1, 2, 3, 4)) -> list:
    """Model-check the a2a protocol: every counts pattern per world,
    the fp8 scale sideband, and call sequences of length 1..4 under
    both buffering regimes (the parity re-expression AND the
    documented TPU collapse). Returns findings."""
    findings = []
    hint = ("the slab/chunk schedule this trace mirrors violates the "
            "a2a protocol — see docs/analysis.md 'a2a-protocol'")

    def emit(trace, extra=()):
        findings.extend(violations_to_findings(
            trace, "a2a-protocol", fix_hint=hint,
            violations=check_trace(trace) + list(extra)))

    for world in worlds:
        for pat_name, counts in counts_patterns(world).items():
            emit(a2a_trace(world, counts,
                           name=f"a2a[w{world} {pat_name}]"))
        emit(a2a_trace(world, counts_patterns(world)["ragged"],
                       fp8_sideband=True,
                       name=f"a2a[w{world} ragged fp8]"))
        for n in seq_lens:
            for buffering in ("fresh", "parity"):
                t = a2a_call_sequence(world, n, buffering=buffering)
                emit(t, extra=check_call_parity(t, buffering))
    return findings


# ---------------------------------------------------------------------------
# Mutators (tests/test_protocol_check.py): the generic dropped-wait /
# doubled-signal mutants come from protocol_model (sem_kind="a2a"
# skips barrier events); swapped call parity is a2a-specific.
# ---------------------------------------------------------------------------

def swap_call_parity(trace: Trace, call: int = 1) -> Trace:
    """Swapped-parity mutant: one call's SIGNALS land in the other
    double-buffer slot while its receivers wait on the right one —
    the cross-call bug class the reference's ``call_count`` protocol
    exists to prevent."""
    t = copy_trace(trace)
    for r, evs in t.events.items():
        for i, e in enumerate(evs):
            if e.kind == "signal" and e.call == call and \
                    e.sem is not None and e.sem[0] in ("a2a", "scale"):
                sem = e.sem[:-1] + (1 - e.sem[-1],)
                evs[i] = dataclasses.replace(e, sem=sem)
    return t
