"""Static model checker for the KV-stream handoff protocol (ISSUE 18).

``serving/kv_stream.py`` moves one disaggregated handoff's KV blocks
from a prefill replica to a decode replica: a content-addressed
``kv_offer``/``kv_need`` negotiation picks the dedup point, then every
needed block ships with a per-block SEQUENCE-NUMBERED completion
signal, and the receiver admits decode-only only once the signal
sequence is contiguous and every needed block has landed
(``HandoffStaging.verify``). Per the protocol-coverage meta-lint
(PR 11), the protocol lands WITH this verifier: the trace builders
execute the kernel's OWN schedule helpers
(:func:`~triton_dist_tpu.serving.kv_stream.ship_schedule` /
``needed_blocks`` — the same functions the sender's loop, the
receiver's contiguity check, and the symm-mem tier follow), so the
protocol and its proof cannot drift.

The model (two ranks: 0 = prefill sender, 1 = decode receiver):

- each scheduled block is a ``signal`` (the shipped payload + its
  sequence-numbered completion) on sem ``("kv", seq)``, drained by the
  sender's ``wait_send`` (the ack);
- the receiver ``wait_recv``s the block's signal BEFORE consuming it
  (no signal before its block — consuming unguarded is the
  ``kvstream.race`` class);
- commit consumes the receiver's locally-held dedup prefix (guard
  ``None`` — local data);
- the coverage oracle demands EVERY block of the handoff consumed
  exactly once — held locally or shipped — so dedup dropping a needed
  block is ``kvstream.coverage``, a dropped completion signal is
  ``kvstream.deadlock``, and a double-ship is
  ``kvstream.signal_wait_imbalance`` (the three mutation classes
  tests/test_disagg.py proves produce DISTINCT codes).
"""

from __future__ import annotations

from triton_dist_tpu.analysis.protocol_model import (
    Ev, Trace, anchor_of, copy_trace, first_event,
    violations_to_findings)

__all__ = [
    "handoff_trace", "verify_kvstream", "drop_signal", "double_ship",
    "dedup_drop_needed", "SENDER", "RECEIVER",
]

SENDER, RECEIVER = 0, 1


def handoff_trace(n_blocks: int, held: int,
                  shipped_from: int | None = None) -> Trace:
    """Event trace of one handoff: ``n_blocks`` total, the receiver's
    prefix cache already holding the first ``held``. The ship plan is
    the kernel's own :func:`ship_schedule`; ``shipped_from`` overrides
    the plan's dedup point WITHOUT changing what the receiver actually
    holds — the ``dedup_drop_needed`` mutant's knob (a broken
    negotiation that trusts a dedup point past the held prefix drops
    a needed block, which the coverage oracle catches)."""
    from triton_dist_tpu.serving import kv_stream
    held = max(0, min(int(held), int(n_blocks)))
    plan = kv_stream.ship_schedule(
        n_blocks, held if shipped_from is None else shipped_from)
    sevs, revs = [], []
    for j, s in plan:
        sem = ("kv", s)
        sevs.append(Ev("signal", SENDER, sem=sem, dst=RECEIVER,
                       call=s))
        sevs.append(Ev("wait_send", SENDER, sem=sem, call=s))
        revs.append(Ev("wait_recv", RECEIVER, sem=sem, call=s))
        revs.append(Ev("consume", RECEIVER, key=("blk", j), guard=sem,
                       call=s))
    # kv_commit: the admission consumes the locally-held dedup prefix
    # too (local data, no delivery guard) — the blocks the negotiation
    # promised were already resident.
    for j in range(held):
        revs.append(Ev("consume", RECEIVER, key=("blk", j)))
    expected = {SENDER: {},
                RECEIVER: {("blk", j): 1 for j in range(n_blocks)}}
    return Trace(
        name=f"kvstream[n{n_blocks} held{held}"
             + (f" ship@{shipped_from}]" if shipped_from is not None
                else "]"),
        world=2, dirs=1,
        events={SENDER: sevs, RECEIVER: revs},
        expected=expected,
        anchor=anchor_of(kv_stream.ship_schedule),
        code_prefix="kvstream")


def verify_kvstream(max_blocks: int = 6) -> list:
    """Model-check every (n_blocks, held) handoff shape up to
    ``max_blocks`` — cold (held 0), every partial overlap, and the
    fully-warm near-zero-byte handoff (held == n_blocks). Returns
    findings (empty == verified)."""
    findings = []
    for n in range(1, int(max_blocks) + 1):
        for held in range(0, n + 1):
            findings.extend(violations_to_findings(
                handoff_trace(n, held), "kvstream-protocol",
                fix_hint=("the ship schedule this trace mirrors "
                          "violates the KV handoff protocol — see "
                          "docs/serving.md 'Disaggregated "
                          "prefill/decode'")))
    return findings


# ---------------------------------------------------------------------------
# Known-bad mutants (tests/test_disagg.py): each must fail with its
# DISTINCT finding code, or the checker is untested.
# ---------------------------------------------------------------------------

def drop_signal(trace: Trace) -> Trace:
    """Dropped completion signal: a block ships but its signal never
    fires — the receiver's wait blocks forever
    (``kvstream.deadlock``)."""
    t = copy_trace(trace)
    r, i = first_event(t, "signal", SENDER, sem_kind="kv")
    del t.events[r][i]
    return t


def double_ship(trace: Trace) -> Trace:
    """Double-shipped block: the same sequence number signals twice —
    a semaphore left nonzero at exit
    (``kvstream.signal_wait_imbalance``)."""
    t = copy_trace(trace)
    r, i = first_event(t, "signal", SENDER, sem_kind="kv")
    t.events[r].insert(i, t.events[r][i])
    return t


def dedup_drop_needed(n_blocks: int, held: int) -> Trace:
    """Dedup drops a needed block: the ship plan trusts a dedup point
    ONE PAST the receiver's held prefix, so block ``held`` is neither
    resident nor shipped (``kvstream.coverage``)."""
    if held >= n_blocks:
        raise ValueError("need at least one non-held block to drop")
    return handoff_trace(n_blocks, held, shipped_from=held + 1)
