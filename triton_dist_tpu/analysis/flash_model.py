"""Static model checker for the distributed flash-decode combine.

``ops/flash_decode.py``'s ``_exchange_and_merge`` is the cross-rank
softmax-state combine (SURVEY §2.5: split-KV decode where one
request's KV spans chips): every rank pushes its (acc, l, m) partial
into every peer's combine-buffer slot — three remote DMAs per peer,
per-(source, buffer) semaphore slots — waits for all peers, then
merges. The merge is only correct if **each rank's partial enters
the softmax rescale exactly once per output row**: a dropped
contributor silently skews the distribution (not a hang — the worst
kind of protocol bug), a doubled one double-counts its weight.

The model executes the kernel's own ``combine_peer`` /
``combine_src`` orderings with concrete ranks and mirrors the
barrier → send-all → wait-all → drain → merge program order. The
merge is modeled as one guarded consume per (source rank, buffer)
pair, so the coverage verdict *is* the exactly-once-merge proof
(``flash.coverage``), alongside the usual balance / deadlock /
arrival-ordering verdicts (``flash.signal_wait_imbalance``,
``flash.deadlock``, ``flash.race``). Both distributed decode kernels
(``_decode_kernel`` and ``_tiled_decode_kernel``) funnel through this
one combine, so one trace shape covers the einsum, tiled and paged
variants.
"""

from __future__ import annotations

import dataclasses
import functools

from triton_dist_tpu.analysis.protocol_model import (
    Ev, Trace, anchor_of, barrier_evs, check_trace, copy_trace,
    violations_to_findings)

__all__ = [
    "combine_trace", "verify_flash_decode", "shift_merge_contributor",
]

#: The three softmax-state buffers exchanged per peer (acc, l, m).
N_BUFS = 3


@functools.lru_cache(maxsize=None)
def _peer_order(me: int, world: int) -> tuple:
    from triton_dist_tpu.ops.flash_decode import combine_peer
    return tuple(int(combine_peer(me, p, world))
                 for p in range(1, world))


@functools.lru_cache(maxsize=None)
def _src_order(me: int, world: int) -> tuple:
    from triton_dist_tpu.ops.flash_decode import combine_src
    return tuple(int(combine_src(me, p, world))
                 for p in range(1, world))


def combine_trace(world: int) -> Trace:
    """Event trace of one ``_exchange_and_merge``: per rank, barrier,
    three signals per peer (per-(source, buffer) semaphore slots),
    arrival waits in ``combine_src`` order, send-side drain, then the
    merge consuming every (source, buffer) partial exactly once."""
    events: dict = {}
    expected: dict = {}
    for me in range(world):
        ev: list = []
        if world > 1:
            ev.extend(barrier_evs(me, world, "fd"))
            for peer in _peer_order(me, world):
                for i in range(N_BUFS):
                    ev.append(Ev("signal", me, sem=("fd", me, peer, i),
                                 dst=peer))
            for src in _src_order(me, world):
                for i in range(N_BUFS):
                    ev.append(Ev("wait_recv", me,
                                 sem=("fd", src, me, i)))
            for peer in _peer_order(me, world):
                for i in range(N_BUFS):
                    ev.append(Ev("wait_send", me,
                                 sem=("fd", me, peer, i)))
        # _merge reads the full (world, ...) stacked buffers: every
        # rank's partial, own slot included, once each.
        for j in range(world):
            for i in range(N_BUFS):
                guard = None if j == me else ("fd", j, me, i)
                ev.append(Ev("consume", me, key=("partial", j, i),
                             guard=guard))
        events[me] = ev
        expected[me] = {("partial", j, i): 1
                        for j in range(world) for i in range(N_BUFS)}
    from triton_dist_tpu.ops import flash_decode
    return Trace(name=f"flash_combine[w{world}]", world=world, dirs=1,
                 events=events, expected=expected,
                 anchor=anchor_of(flash_decode._exchange_and_merge),
                 code_prefix="flash")


def verify_flash_decode(worlds=range(1, 9)) -> list:
    """Model-check the combine for every world size; returns
    findings."""
    findings = []
    for world in worlds:
        findings.extend(violations_to_findings(
            combine_trace(world), "flash-decode-protocol",
            fix_hint=("the combine this trace mirrors violates the "
                      "exactly-once softmax-state merge — see "
                      "docs/analysis.md 'flash-decode-protocol'")))
    return findings


def shift_merge_contributor(trace: Trace, rank: int = 0) -> Trace:
    """Off-by-one merge-contributor mutant: the merge at ``rank``
    reads one peer's slot twice and skips another's — the silent
    distribution-skew bug class (no hang, wrong softmax)."""
    t = copy_trace(trace)
    evs = t.events[rank]
    for i, e in enumerate(evs):
        if e.kind == "consume" and e.key[1] != rank:
            j = (e.key[1] + 1) % t.world
            guard = None if j == rank else ("fd", j, rank, e.key[2])
            evs[i] = dataclasses.replace(
                e, key=("partial", j, e.key[2]), guard=guard)
            break
    return t
