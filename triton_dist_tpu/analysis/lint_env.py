"""Env-knob registry pass: every ``TDT_*`` knob is documented, and
integer knobs parse through ``obs.registry.env_int``.

An undocumented knob is configuration surface nobody can discover;
hand-rolled ``int(os.environ.get(...))`` parsing scatters the
validation (empty-string handling, minimums, error wording) that
``env_int`` centralizes. The pass scans the package (plus the
top-level entry scripts) for ``TDT_``-prefixed string constants and
flags (a) knobs that appear in no ``docs/*.md``, (b) ``int(...)``
applied — directly or through a local variable — to an env read of a
knob.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["collect_knobs", "documented_knobs", "run"]

_KNOB = re.compile(r"^TDT_[A-Z0-9_]+$")
_KNOB_IN_DOCS = re.compile(r"TDT_[A-Z0-9_]+")


def _env_read_knob(node):
    """Knob name when ``node`` reads a TDT_* env var:
    ``os.environ.get("TDT_X", ...)`` / ``os.getenv("TDT_X")`` /
    ``os.environ["TDT_X"]`` / ``env_int("TDT_X", ...)``-style helpers,
    optionally wrapped in ``.strip()``/``.lower()`` chains."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("strip", "lower"):
            return _env_read_knob(f.value)
        name = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, "id", None)
        if name in ("get", "getenv", "setdefault") and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                    and _KNOB.match(a.value):
                return a.value
    if isinstance(node, ast.Subscript):
        s = node.slice
        if isinstance(s, ast.Constant) and isinstance(s.value, str) \
                and _KNOB.match(s.value):
            return s.value
    return None


def _scope_walk(scope):
    """Descendants of ``scope`` excluding nested function subtrees
    (each function is its own taint scope)."""
    from collections import deque
    queue = deque(ast.iter_child_nodes(scope))
    while queue:   # breadth-first, like ast.walk: assignments at a
        node = queue.popleft()   # shallower level taint deeper reads
        yield node
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            queue.extend(ast.iter_child_nodes(node))


def collect_knobs(files):
    """(knob, file, line) for every TDT_* string constant, plus
    int-parse findings-to-be as (knob, file, line) in the second
    list."""
    mentions = []
    int_parses = []
    for py in files:
        try:
            tree = ast.parse(Path(py).read_text(), filename=str(py))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and _KNOB.match(node.value):
                mentions.append((node.value, str(py), node.lineno))
        # One taint scope per function (module top level is a scope
        # too, with function bodies excluded): a name assigned from an
        # env read taints later int(name) calls in the SAME scope only.
        scopes = [n for n in ast.walk(tree)
                  if isinstance(n, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))]
        scopes.append(tree)
        seen_parses = set()
        for fn in scopes:
            tainted = {}   # local name -> knob it was read from
            for node in _scope_walk(fn):
                if isinstance(node, ast.Assign):
                    knob = next(
                        (k for sub in ast.walk(node.value)
                         if (k := _env_read_knob(sub))), None)
                    if knob:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                tainted[tgt.id] = knob
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Name) \
                        and node.func.id == "int" and node.args:
                    arg = node.args[0]
                    knob = next(
                        (k for sub in ast.walk(arg)
                         if (k := _env_read_knob(sub))), None)
                    if knob is None:
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Name) \
                                    and sub.id in tainted:
                                knob = tainted[sub.id]
                                break
                    if knob and (knob, node.lineno) not in seen_parses:
                        seen_parses.add((knob, node.lineno))
                        int_parses.append((knob, str(py), node.lineno))
    return mentions, int_parses


def documented_knobs(docs_dir) -> set:
    knobs = set()
    for md in Path(docs_dir).glob("*.md"):
        knobs |= set(_KNOB_IN_DOCS.findall(md.read_text()))
    return knobs


def run(root=None, files=None, docs_dir=None) -> list:
    if root is None:
        import triton_dist_tpu
        root = Path(triton_dist_tpu.__file__).parent.parent
    root = Path(root)
    if files is None:
        files = sorted((root / "triton_dist_tpu").rglob("*.py"))
        for extra in ("bench.py", "tpu_smoke.py"):
            if (root / extra).exists():
                files.append(root / extra)
    if docs_dir is None:
        docs_dir = root / "docs"
    if not Path(docs_dir).exists():
        return [Finding(
            code="lint.env_docs_missing", severity="warning",
            message=f"docs dir not found at {docs_dir} — env-knob "
                    f"documentation check skipped",
            pass_name="env-knobs")]
    documented = documented_knobs(docs_dir)
    mentions, int_parses = collect_knobs(files)
    findings = []
    reported = set()
    for knob, file, line in mentions:
        if knob in documented or knob in reported:
            continue
        reported.add(knob)
        findings.append(Finding(
            code="lint.env_undocumented",
            message=f"env knob {knob} is read here but documented in "
                    f"no docs/*.md",
            file=file, line=line, pass_name="env-knobs",
            fix_hint="add it to the knob table of the owning doc "
                     "(docs/observability.md 'Knobs', "
                     "docs/resilience.md, ...)"))
    for knob, file, line in int_parses:
        findings.append(Finding(
            code="lint.env_int_parse",
            message=f"hand-rolled int() parse of {knob} — integer "
                    f"knobs go through obs.registry.env_int "
                    f"(validated, shared error wording)",
            file=file, line=line, pass_name="env-knobs",
            fix_hint="from triton_dist_tpu.obs import env_int; "
                     f"env_int({knob!r}, default, minimum=...)"))
    return findings
