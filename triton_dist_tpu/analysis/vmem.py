"""Static VMEM-budget pass: every fused-family autotune candidate must
fit the declared-footprint cap *before* any compile.

Two rounds of smoke queues were wedged by compile hangs a static
VMEM/shape check could have rejected pre-compile (ROADMAP item 1).
This pass closes that hole from two sides:

- :func:`vet_candidate` turns one (op, config, shape) into a Finding
  when ``tools.perf_model.declared_footprint`` exceeds the cap — the
  same gate ``tools.autotuner.autotune(vet=...)`` applies to every
  sweep candidate at runtime, and ``tpu_smoke.py``'s preflight applies
  before a queue starts.
- the registered ``vmem-budget`` pass sweeps the FULL candidate tables
  (``tier_caps=False``, generated against ``TUNED_VMEM_BUDGET``) for
  representative shapes x worlds 1..8 and flags any entry over
  ``HARD_FOOTPRINT_CAP`` — a config-generator change that starts
  emitting uncompilable candidates fails CI, not a smoke queue.

Complementary to ``testing/vmem.assert_vmem_within``: that checker
intercepts real ``pallas_call``s under ``jax.eval_shape`` (exact for
the kernel it traces, but it must build the kernel); this one is
formula-based over config dicts (``perf_model.declared_footprint``),
so it can sweep whole candidate tables in microseconds with no jax
tracing at all.
"""

from __future__ import annotations

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["vet_candidate", "sweep_candidate_tables",
           "sweep_comm_buffers"]

#: Representative sweep shapes: the bench shape family (docs/perf.md)
#: at bf16. (m, k, n) are GLOBAL dims; per-op local dims derive from
#: the world size exactly as the op entries derive them.
SWEEP_SHAPES = ((4096, 4096, 4096), (8192, 8192, 8192))

#: Comm-buffer sweep shapes (ISSUE 12 satellite). all_to_all: the
#: reference's headline LL config (128 tokens/rank) at the serving
#: hidden size on the bf16 wire AND at hidden 7168 on the fp8/int8
#: wire — the configuration the reference actually runs its headline
#: at (SURVEY §6); a hidden-7168 *bf16* wire at world 8 would exceed
#: the cap, which is exactly the class of refusal this sweep makes
#: static. moe_reduce_rs: the bench shape (T=2048, topk=2, I=4096,
#: H=4096, docs/perf.md) at the default tile config.
A2A_SWEEP = ((128, 4096, 2), (128, 7168, 1))
MOE_RS_SWEEP = ((2048, 2, 4096, 4096),)


def _generator_anchor(op: str) -> tuple:
    """(file, line) of the config generator (or context/config class)
    that emits candidates for ``op`` — the code a ``vmem.over_budget``
    finding asks you to change (a pass-wide anchor would let one
    suppression pragma mute the whole finding class)."""
    import inspect
    from triton_dist_tpu.ops import (all_to_all, allgather_gemm,
                                     gemm_reduce_scatter,
                                     moe_reduce_rs)
    gen = {"ag_gemm": allgather_gemm.ag_gemm_configs,
           "ag_swiglu": allgather_gemm.ag_swiglu_configs,
           "gemm_rs": gemm_reduce_scatter.gemm_rs_configs,
           "gemm_ar": gemm_reduce_scatter.gemm_rs_configs,
           "all_to_all": all_to_all.AllToAllContext,
           "moe_reduce_rs": moe_reduce_rs.MoEReduceRSContext}.get(op)
    if gen is None:
        return None, None
    try:
        _, line = inspect.getsourcelines(gen)
        return inspect.getsourcefile(gen), line
    except (OSError, TypeError):  # pragma: no cover
        return None, None


def vet_candidate(op: str, cfg: dict, *, cap: int | None = None,
                  **dims) -> Finding | None:
    """One candidate's static VMEM verdict (None == fits)."""
    from triton_dist_tpu.tools import perf_model as _pm
    reason = _pm.vet_vmem(op, cfg, cap=cap, **dims)
    if reason is None:
        return None
    file, line = _generator_anchor(op)
    return Finding(
        code="vmem.over_budget", message=reason, file=file,
        line=line, pass_name="vmem-budget",
        fix_hint="shrink block_m/block_n/block_k or drop the config "
                 "from the table; HARD_FOOTPRINT_CAP rationale in "
                 "ops/common.py")


def sweep_candidate_tables(worlds=range(1, 9)) -> list:
    """Findings for every over-cap candidate any config table emits at
    the representative shapes (empty == every sweep the autotuner
    could run compiles under the cap)."""
    from triton_dist_tpu.ops.allgather_gemm import (
        ag_gemm_configs, ag_swiglu_configs)
    from triton_dist_tpu.ops.common import TUNED_VMEM_BUDGET
    from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs_configs

    item = 2  # bf16 — the fused family's serving dtype
    findings = []
    for world in worlds:
        for m, k, n in SWEEP_SHAPES:
            rows = m // world
            n_loc = n // world
            k_loc = k // world
            if not (rows and n_loc and k_loc):
                continue
            for cfg in ag_gemm_configs(m, rows, k, n_loc, item,
                                       TUNED_VMEM_BUDGET,
                                       tier_caps=False):
                f = vet_candidate("ag_gemm", cfg, rows=rows, m=m, k=k,
                                  n_loc=n_loc, itemsize=item,
                                  world=world)
                if f:
                    findings.append(f)
            for cfg in ag_swiglu_configs(rows, k, n_loc, item,
                                         TUNED_VMEM_BUDGET,
                                         tier_caps=False):
                f = vet_candidate("ag_swiglu", cfg, rows=rows, k=k,
                                  itemsize=item)
                if f:
                    findings.append(f)
            for cfg in gemm_rs_configs(m, rows, k_loc, n, item, world,
                                       TUNED_VMEM_BUDGET,
                                       tier_caps=False):
                f = vet_candidate("gemm_rs", cfg, rows=rows, m=m,
                                  k_loc=k_loc, n=n, itemsize=item,
                                  world=world)
                if f:
                    findings.append(f)
    return findings


def sweep_comm_buffers(worlds=range(1, 9), a2a_shapes=None,
                       moe_shapes=None) -> list:
    """Findings for comm-kernel buffer footprints beyond the fused
    GEMM family (ISSUE 12 satellite): the all-to-all's whole-in-VMEM
    send/recv slabs (per-(slab, chunk) semaphore arrays are not VMEM)
    and the fused MoE-RS scratch, at bench shapes for worlds 1..8.
    Anchored at each op's own config site (``AllToAllContext`` /
    ``MoEReduceRSContext``) so one pragma cannot mute the class."""
    from triton_dist_tpu.ops.common import DEFAULT_VMEM_BUDGET
    findings = []
    for world in worlds:
        for capacity, h, item in (a2a_shapes or A2A_SWEEP):
            f = vet_candidate("all_to_all",
                              {"capacity": capacity, "h": h},
                              rows=0, itemsize=item, world=world)
            if f:
                findings.append(f)
        for t, topk, inter, hid in (moe_shapes or MOE_RS_SWEEP):
            if t % world:
                continue
            f = vet_candidate(
                "moe_reduce_rs",
                {"h": hid, "i_loc": max(inter // world, 1),
                 "block_m": 128, "block_h": 512,
                 "vmem_budget": DEFAULT_VMEM_BUDGET},
                rows=t // world, itemsize=2, world=world)
            if f:
                findings.append(f)
    return findings
