"""``triton_dist_tpu.analysis`` — static-analysis framework.

A plugin pass API over a shared findings model (docs/analysis.md).
Each pass is a function ``(repo_root: Path) -> list[Finding]``
registered under a stable name; ``run_passes`` runs a selection,
applies inline ``# tdt: ignore[...]`` suppression pragmas, and hands
the surviving findings to the ``tools/tdt_check.py`` driver (JSON or
human output, nonzero exit on errors). The quick tier runs every pass
over the repo (tests/test_tdt_check.py, tests/test_protocol_check.py)
and ``tpu_smoke.py`` runs them as a preflight, so a protocol or
contract regression fails CI — not a smoke queue, and not a chip.

Built-in passes:

- ``ring-protocol`` — model-checks the fused GEMM family's ring
  signal/wait protocols for worlds 1..8 x both ring directions
  (:mod:`.ring_model`, on the shared :mod:`.protocol_model` core);
- ``a2a-protocol`` — the EP all-to-all's slab/chunk push: per-(slab,
  chunk) semaphore accounting over ragged/zero/one-hot counts, the
  fp8 scale sideband, and cross-call composition proving the
  double-buffer call-parity invariant for call sequences 1..4 —
  including the documented TPU collapse case (:mod:`.a2a_model`);
- ``p2p-protocol`` — the PP ``_shift_kernel`` hop protocol, composed
  over mixed ±delta pipelines (:mod:`.p2p_model`);
- ``kvstream-protocol`` — the disaggregated prefill/decode KV-handoff
  offer/need/ship/signal sequence over the kernel's own dedup/ship
  schedule helpers, every (n_blocks, held) shape
  (:mod:`.kvstream_model`);
- ``flash-decode-protocol`` — the distributed flash-decode softmax-
  state combine: each rank's (acc, l, m) partial merges exactly once
  (:mod:`.flash_model`);
- ``protocol-coverage`` — meta-lint: every semaphore/DMA-using module
  under ``ops/`` is claimed by a registered verifier pass, so the
  next comm kernel cannot land unverified (:mod:`.lint_protocol`);
- ``vmem-budget`` — every autotune candidate the config tables can
  emit fits the declared-footprint cap, statically — now including
  the all-to-all send/recv slabs and the fused MoE-RS scratch at
  bench shapes for worlds 1..8 (:mod:`.vmem`);
- ``metric-catalog`` — emitted metrics and docs/observability.md
  agree, both directions (:mod:`.lint_metrics`);
- ``env-knobs`` — every ``TDT_*`` knob documented; integer knobs
  parse via ``obs.registry.env_int`` (:mod:`.lint_env`);
- ``trace-balance`` — host-side trace emitters close what they open
  (:mod:`.lint_trace`);
- ``fallback-coverage`` — every public op entry has a registered XLA
  escape hatch (:mod:`.lint_fallback`, migrated from
  ``tools/fallback_lint.py``);
- ``annotation-coverage`` — every ``@resilient`` invocation executes
  under a ``device.<op>.*`` profiler label and the pump sampler keeps
  its ``device.step`` window, so ``obs.devprof``'s measured
  attribution never silently reads empty windows
  (:mod:`.lint_annotations`).

Each pass declares the repo files it watches (``Pass.watches``,
repo-relative globs; a trailing ``/`` matches the subtree) so the
driver's ``--changed`` mode can run only the passes whose inputs a
diff touched — the fast pre-commit loop.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from pathlib import Path

from triton_dist_tpu.analysis.findings import (  # noqa: F401
    Finding, SEVERITIES, exit_code, filter_suppressed, render_human,
    render_json)

__all__ = ["Finding", "Pass", "PASSES", "register_pass", "repo_root",
           "run_passes", "select_passes_for", "watch_match",
           "exit_code", "filter_suppressed", "render_human",
           "render_json"]


@dataclasses.dataclass(frozen=True)
class Pass:
    name: str
    description: str
    fn: object     # (root: Path) -> list[Finding]
    watches: tuple = ()   # repo-relative globs; () = always run


PASSES: dict = {}


def register_pass(name: str, description: str, watches: tuple = ()):
    """Decorator adding a pass to the registry (docs/analysis.md
    "Adding a pass"). Pass functions take the repo root and return
    findings; they must be side-effect-free and fast enough for the
    quick tier. ``watches`` lists the repo-relative paths/globs whose
    change makes the pass worth re-running (``--changed``); an empty
    tuple means the pass always runs."""
    def deco(fn):
        if name in PASSES:
            raise ValueError(f"pass {name!r} already registered")
        PASSES[name] = Pass(name=name, description=description, fn=fn,
                            watches=tuple(watches))
        return fn
    return deco


def watch_match(path: str, pattern: str) -> bool:
    """One changed path against one watch pattern: a trailing ``/``
    is a subtree prefix, anything else is an fnmatch glob on the
    repo-relative posix path."""
    path = path.replace("\\", "/")
    if pattern.endswith("/"):
        return path.startswith(pattern)
    return fnmatch.fnmatch(path, pattern)


def select_passes_for(changed_files) -> list:
    """Pass names worth running for a set of changed repo-relative
    paths: every pass with no declared watches, plus every pass one
    of whose watch patterns matches a changed file. Deterministic
    registry order."""
    changed = list(changed_files)
    names = []
    for name, p in PASSES.items():
        if not p.watches or any(watch_match(f, pat)
                                for f in changed for pat in p.watches):
            names.append(name)
    return names


def repo_root() -> Path:
    import triton_dist_tpu
    return Path(triton_dist_tpu.__file__).parent.parent


def run_passes(root=None, names=None, apply_suppression=True) -> list:
    """Run passes (all by default) and return surviving findings,
    stamped with their pass name and sorted errors-first."""
    root = Path(root) if root is not None else repo_root()
    if names is None:
        names = list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}; "
                         f"available: {sorted(PASSES)}")
    findings = []
    for name in names:
        for f in PASSES[name].fn(root):
            if not f.pass_name:
                f = dataclasses.replace(f, pass_name=name)
            findings.append(f)
    if apply_suppression:
        findings = filter_suppressed(findings)
    findings.sort(key=lambda f: (f.severity != "error", f.file or "",
                                 f.line or 0, f.code))
    return findings


# -- built-in pass registrations -------------------------------------------
# Heavy imports (jax via ops/) stay inside the pass bodies so importing
# the framework itself is cheap.

_CORE = ("triton_dist_tpu/analysis/protocol_model.py",
         "triton_dist_tpu/analysis/findings.py")


@register_pass("ring-protocol",
               "model-check the fused-family ring schedules, worlds "
               "1..8 x both ring_dirs",
               watches=_CORE + (
                   "triton_dist_tpu/analysis/ring_model.py",
                   "triton_dist_tpu/ops/common.py",
                   "triton_dist_tpu/ops/allgather_gemm.py",
                   "triton_dist_tpu/ops/gemm_reduce_scatter.py"))
def _ring_pass(root):
    from triton_dist_tpu.analysis import ring_model
    return ring_model.verify_family()


@register_pass("a2a-protocol",
               "model-check the EP all-to-all slab/chunk protocol + "
               "cross-call double-buffer parity, worlds 1..8 x call "
               "sequences 1..4",
               watches=_CORE + (
                   "triton_dist_tpu/analysis/a2a_model.py",
                   "triton_dist_tpu/ops/all_to_all.py"))
def _a2a_pass(root):
    from triton_dist_tpu.analysis import a2a_model
    return a2a_model.verify_a2a()


@register_pass("p2p-protocol",
               "model-check the PP shift-hop protocol over mixed "
               "±delta pipelines, worlds 1..8",
               watches=_CORE + (
                   "triton_dist_tpu/analysis/p2p_model.py",
                   "triton_dist_tpu/ops/p2p.py"))
def _p2p_pass(root):
    from triton_dist_tpu.analysis import p2p_model
    return p2p_model.verify_p2p()


@register_pass("kvstream-protocol",
               "model-check the disaggregated KV-handoff offer/need/"
               "ship/signal protocol over every (n_blocks, held) "
               "dedup shape",
               watches=_CORE + (
                   "triton_dist_tpu/analysis/kvstream_model.py",
                   "triton_dist_tpu/serving/kv_stream.py",
                   "triton_dist_tpu/serving/disagg.py"))
def _kvstream_pass(root):
    from triton_dist_tpu.analysis import kvstream_model
    return kvstream_model.verify_kvstream()


@register_pass("flash-decode-protocol",
               "model-check the distributed flash-decode softmax-"
               "state combine (exactly-once merge), worlds 1..8",
               watches=_CORE + (
                   "triton_dist_tpu/analysis/flash_model.py",
                   "triton_dist_tpu/ops/flash_decode.py"))
def _flash_pass(root):
    from triton_dist_tpu.analysis import flash_model
    return flash_model.verify_flash_decode()


@register_pass("protocol-coverage",
               "every semaphore/DMA-using ops/ module is claimed by "
               "a registered verifier pass",
               watches=("triton_dist_tpu/ops/",
                        "triton_dist_tpu/analysis/lint_protocol.py",
                        "triton_dist_tpu/analysis/__init__.py"))
def _protocol_coverage_pass(root):
    from triton_dist_tpu.analysis import lint_protocol
    return lint_protocol.run(root)


@register_pass("vmem-budget",
               "every autotune candidate + comm-buffer footprint "
               "fits HARD_FOOTPRINT_CAP statically (no compile)",
               watches=("triton_dist_tpu/analysis/vmem.py",
                        "triton_dist_tpu/tools/perf_model.py",
                        "triton_dist_tpu/ops/common.py",
                        "triton_dist_tpu/ops/allgather_gemm.py",
                        "triton_dist_tpu/ops/gemm_reduce_scatter.py",
                        "triton_dist_tpu/ops/all_to_all.py",
                        "triton_dist_tpu/ops/moe_reduce_rs.py"))
def _vmem_pass(root):
    from triton_dist_tpu.analysis import vmem
    return vmem.sweep_candidate_tables() + vmem.sweep_comm_buffers()


@register_pass("metric-catalog",
               "emitted metrics and the docs/observability.md catalog "
               "agree, both directions",
               # The package-wide glob already covers serving/ and
               # models/spec.py; the explicit entries pin the ISSUE-13
               # contract (spec telemetry stays cataloged), the
               # ISSUE-14 one (fleet/fleet_top telemetry likewise),
               # the ISSUE-15 one (router + chaos-harness telemetry),
               # the ISSUE-16 one (history-plane telemetry), and the
               # ISSUE-18 one (disagg stream/handoff telemetry)
               # against a future narrowing of the package glob.
               watches=("triton_dist_tpu/", "docs/observability.md",
                        "triton_dist_tpu/serving/",
                        "triton_dist_tpu/serving/router.py",
                        "triton_dist_tpu/serving/kv_stream.py",
                        "triton_dist_tpu/serving/disagg.py",
                        "triton_dist_tpu/models/spec.py",
                        "triton_dist_tpu/obs/fleet.py",
                        "triton_dist_tpu/obs/history.py",
                        "triton_dist_tpu/testing/chaos.py",
                        "triton_dist_tpu/tools/fleet_top.py"))
def _metrics_pass(root):
    from triton_dist_tpu.analysis import lint_metrics
    return lint_metrics.run(root)


@register_pass("env-knobs",
               "every TDT_* knob documented; integer knobs via "
               "obs.registry.env_int",
               watches=("triton_dist_tpu/", "docs/"))
def _env_pass(root):
    from triton_dist_tpu.analysis import lint_env
    return lint_env.run(root)


@register_pass("trace-balance",
               "host-side trace.begin/end emitters are balanced",
               watches=("triton_dist_tpu/",))
def _trace_pass(root):
    from triton_dist_tpu.analysis import lint_trace
    return lint_trace.run(root)


@register_pass("fallback-coverage",
               "every public op entry has a registered XLA escape "
               "hatch",
               watches=("triton_dist_tpu/ops/",
                        "triton_dist_tpu/resilience/",
                        "triton_dist_tpu/analysis/lint_fallback.py"))
def _fallback_pass(root):
    from triton_dist_tpu.analysis import lint_fallback
    return lint_fallback.collect_findings()


@register_pass("annotation-coverage",
               "every @resilient invocation runs under a device.<op>.* "
               "profiler label; the pump sampler keeps device.step",
               # serving/ as a subtree (not just scheduler.py): the
               # pump's step labels now name three paths (mega/plain/
               # spec — ISSUE 13), and a spec change that re-routes the
               # decode verb must re-run this pass; models/spec.py
               # rides along for the same reason. The fleet surfaces
               # (ISSUE 14) ride too: a fleet-plane edit that touched
               # the pump's read path must re-verify the device.step
               # labels under --changed. The ISSUE-15 router + chaos
               # harness ride for the same reason: the chaos wedge
               # hooks into the pump's work region and the router
               # re-drives the serving path end to end. The ISSUE-16
               # history sampler rides because it lives inside the
               # pump's lifecycle (scheduler-owned thread peeking the
               # registry the labeled step updates). The ISSUE-18
               # disagg plane rides because the prefill-side kv_export
               # hook runs inside the pump's record path and the
               # decode-side adopt bypasses the labeled prefill step.
               watches=("triton_dist_tpu/resilience/router.py",
                        "triton_dist_tpu/obs/devprof.py",
                        "triton_dist_tpu/serving/",
                        "triton_dist_tpu/serving/router.py",
                        "triton_dist_tpu/serving/kv_stream.py",
                        "triton_dist_tpu/serving/disagg.py",
                        "triton_dist_tpu/models/spec.py",
                        "triton_dist_tpu/obs/fleet.py",
                        "triton_dist_tpu/obs/history.py",
                        "triton_dist_tpu/testing/chaos.py",
                        "triton_dist_tpu/tools/fleet_top.py",
                        "triton_dist_tpu/analysis/lint_annotations.py"))
def _annotation_pass(root):
    from triton_dist_tpu.analysis import lint_annotations
    return lint_annotations.run(root)
