"""``triton_dist_tpu.analysis`` — static-analysis framework.

A plugin pass API over a shared findings model (docs/analysis.md).
Each pass is a function ``(repo_root: Path) -> list[Finding]``
registered under a stable name; ``run_passes`` runs a selection,
applies inline ``# tdt: ignore[...]`` suppression pragmas, and hands
the surviving findings to the ``tools/tdt_check.py`` driver (JSON or
human output, nonzero exit on errors). The quick tier runs every pass
over the repo (tests/test_tdt_check.py) and ``tpu_smoke.py`` runs
them as a preflight, so a protocol or contract regression fails CI —
not a smoke queue, and not a chip.

Built-in passes:

- ``ring-protocol`` — model-checks the fused GEMM family's ring
  signal/wait protocols for worlds 1..8 x both ring directions
  (:mod:`.ring_model`);
- ``vmem-budget`` — every autotune candidate the config tables can
  emit fits the declared-footprint cap, statically (:mod:`.vmem`);
- ``metric-catalog`` — emitted metrics and docs/observability.md
  agree, both directions (:mod:`.lint_metrics`);
- ``env-knobs`` — every ``TDT_*`` knob documented; integer knobs
  parse via ``obs.registry.env_int`` (:mod:`.lint_env`);
- ``trace-balance`` — host-side trace emitters close what they open
  (:mod:`.lint_trace`);
- ``fallback-coverage`` — every public op entry has a registered XLA
  escape hatch (:mod:`.lint_fallback`, migrated from
  ``tools/fallback_lint.py``);
- ``annotation-coverage`` — every ``@resilient`` invocation executes
  under a ``device.<op>.*`` profiler label and the pump sampler keeps
  its ``device.step`` window, so ``obs.devprof``'s measured
  attribution never silently reads empty windows
  (:mod:`.lint_annotations`).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

from triton_dist_tpu.analysis.findings import (  # noqa: F401
    Finding, SEVERITIES, exit_code, filter_suppressed, render_human,
    render_json)

__all__ = ["Finding", "Pass", "PASSES", "register_pass", "repo_root",
           "run_passes", "exit_code", "filter_suppressed",
           "render_human", "render_json"]


@dataclasses.dataclass(frozen=True)
class Pass:
    name: str
    description: str
    fn: object     # (root: Path) -> list[Finding]


PASSES: dict = {}


def register_pass(name: str, description: str):
    """Decorator adding a pass to the registry (docs/analysis.md
    "Adding a pass"). Pass functions take the repo root and return
    findings; they must be side-effect-free and fast enough for the
    quick tier."""
    def deco(fn):
        if name in PASSES:
            raise ValueError(f"pass {name!r} already registered")
        PASSES[name] = Pass(name=name, description=description, fn=fn)
        return fn
    return deco


def repo_root() -> Path:
    import triton_dist_tpu
    return Path(triton_dist_tpu.__file__).parent.parent


def run_passes(root=None, names=None, apply_suppression=True) -> list:
    """Run passes (all by default) and return surviving findings,
    stamped with their pass name and sorted errors-first."""
    root = Path(root) if root is not None else repo_root()
    if names is None:
        names = list(PASSES)
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        raise ValueError(f"unknown pass(es): {unknown}; "
                         f"available: {sorted(PASSES)}")
    findings = []
    for name in names:
        for f in PASSES[name].fn(root):
            if not f.pass_name:
                f = dataclasses.replace(f, pass_name=name)
            findings.append(f)
    if apply_suppression:
        findings = filter_suppressed(findings)
    findings.sort(key=lambda f: (f.severity != "error", f.file or "",
                                 f.line or 0, f.code))
    return findings


# -- built-in pass registrations -------------------------------------------
# Heavy imports (jax via ops/) stay inside the pass bodies so importing
# the framework itself is cheap.

@register_pass("ring-protocol",
               "model-check the fused-family ring schedules, worlds "
               "1..8 x both ring_dirs")
def _ring_pass(root):
    from triton_dist_tpu.analysis import ring_model
    return ring_model.verify_family()


@register_pass("vmem-budget",
               "every autotune candidate fits HARD_FOOTPRINT_CAP "
               "statically (no compile)")
def _vmem_pass(root):
    from triton_dist_tpu.analysis import vmem
    return vmem.sweep_candidate_tables()


@register_pass("metric-catalog",
               "emitted metrics and the docs/observability.md catalog "
               "agree, both directions")
def _metrics_pass(root):
    from triton_dist_tpu.analysis import lint_metrics
    return lint_metrics.run(root)


@register_pass("env-knobs",
               "every TDT_* knob documented; integer knobs via "
               "obs.registry.env_int")
def _env_pass(root):
    from triton_dist_tpu.analysis import lint_env
    return lint_env.run(root)


@register_pass("trace-balance",
               "host-side trace.begin/end emitters are balanced")
def _trace_pass(root):
    from triton_dist_tpu.analysis import lint_trace
    return lint_trace.run(root)


@register_pass("fallback-coverage",
               "every public op entry has a registered XLA escape "
               "hatch")
def _fallback_pass(root):
    from triton_dist_tpu.analysis import lint_fallback
    return lint_fallback.collect_findings()


@register_pass("annotation-coverage",
               "every @resilient invocation runs under a device.<op>.* "
               "profiler label; the pump sampler keeps device.step")
def _annotation_pass(root):
    from triton_dist_tpu.analysis import lint_annotations
    return lint_annotations.run(root)
