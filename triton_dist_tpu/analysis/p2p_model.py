"""Static model checker for the PP ``_shift_kernel`` hop protocol.

``ops/p2p.py``'s Pallas path is one remote DMA per rank per hop: push
the local buffer to rank ``me+delta``, wait the incoming DMA's recv
semaphore, drain the outgoing send semaphore — the reference's p2p
set/wait signal pair collapsed into the DMA semaphore pair. The
ROADMAP's disaggregated prefill/decode tier (item 2) makes this the
transport for KV-block streaming, so its protocol gets the same
static proof the rings and the a2a have: a signal/wait imbalance is a
CI failure, not a fleet hang.

The model executes the kernel's own :func:`~triton_dist_tpu.ops.p2p.
shift_partners` with concrete ranks and mirrors the kernel's
barrier → start → wait_recv → wait_send program order. A **pipeline**
(:func:`pipeline_trace`) composes a sequence of hops with mixed
±delta values — each stage a separate ``pallas_call`` with fresh
semaphores, per-rank concatenation via ``concat_traces`` — and the
composed verdicts prove a mixed-direction pipeline cannot deadlock
(``p2p.deadlock``), double-deliver (``p2p.coverage``), read in-flight
data (``p2p.race``) or leave a semaphore nonzero
(``p2p.signal_wait_imbalance``).
"""

from __future__ import annotations

import dataclasses
import functools

from triton_dist_tpu.analysis.protocol_model import (
    Ev, Trace, anchor_of, barrier_evs, check_trace, concat_traces,
    copy_trace, violations_to_findings)

__all__ = [
    "shift_trace", "pipeline_trace", "verify_p2p", "swap_delta",
    "PIPELINES",
]

#: Representative hop sequences: single hops both ways, a long-range
#: hop, forward-backward bubbles, and a mixed ±delta pipeline — the
#: shapes a 1F1B/interleaved PP schedule issues.
PIPELINES = (
    (1,), (-1,), (2,),
    (1, -1), (-1, 1),
    (1, 1, -1),
    (1, -1, 2, -2),
)


@functools.lru_cache(maxsize=None)
def _partners(me: int, delta: int, world: int) -> tuple:
    """(dst, src) from the kernel's own ``shift_partners``."""
    from triton_dist_tpu.ops.p2p import shift_partners
    dst, src = shift_partners(me, delta, world)
    return int(dst), int(src)


def shift_trace(world: int, delta: int, stage: int = 0) -> Trace:
    """Event trace of one ``pp_shift`` hop (one ``pallas_call``:
    fresh single DMA semaphore pair per rank, namespaced by
    ``stage`` for composition). ``world == 1`` mirrors the host-side
    early return (no kernel, identity)."""
    events: dict = {}
    expected: dict = {}
    for me in range(world):
        if world == 1:
            events[me] = [Ev("consume", me, key=("stage", stage, me),
                             call=stage)]
            expected[me] = {("stage", stage, me): 1}
            continue
        dst, src = _partners(me, delta, world)
        sem = ("p2p", stage)
        ev = barrier_evs(me, world, ("p2p", stage))
        ev.append(Ev("signal", me, sem=sem, dst=dst, call=stage))
        ev.append(Ev("wait_recv", me, sem=sem, call=stage))
        ev.append(Ev("consume", me, key=("stage", stage, src),
                     guard=sem, call=stage))
        ev.append(Ev("wait_send", me, sem=sem, call=stage))
        events[me] = ev
        # Coverage oracle from the CONTRACT (pp_shift docstring:
        # stage i holds what stage i-delta had), independent of
        # shift_partners — so a bug in the kernel's own partner math
        # shows up as a coverage mismatch, not a matching mirror.
        expected[me] = {("stage", stage, (me - delta) % world): 1}
    from triton_dist_tpu.ops import p2p
    return Trace(name=f"p2p[w{world} d{delta:+d} s{stage}]",
                 world=world, dirs=1, events=events, expected=expected,
                 anchor=anchor_of(p2p._shift_kernel),
                 code_prefix="p2p")


def pipeline_trace(world: int, deltas) -> Trace:
    """Composed trace of a hop pipeline: stage ``k`` shifts by
    ``deltas[k]``. Each stage's semaphores are stage-fresh (one
    ``pallas_call`` each), so proving the composition reduces to
    proving every stage balances and drains — which the composed
    verdicts check rather than assume."""
    traces = [shift_trace(world, d, stage=k)
              for k, d in enumerate(deltas)]
    return concat_traces(
        traces,
        f"p2p_pipe[w{world} " +
        ",".join(f"{d:+d}" for d in deltas) + "]")


def verify_p2p(worlds=range(1, 9), pipelines=PIPELINES) -> list:
    """Model-check every hop pipeline shape per world; returns
    findings."""
    findings = []
    for world in worlds:
        for deltas in pipelines:
            findings.extend(violations_to_findings(
                pipeline_trace(world, deltas), "p2p-protocol",
                fix_hint=("the shift schedule this trace mirrors "
                          "violates the p2p hop protocol — see "
                          "docs/analysis.md 'p2p-protocol'")))
    return findings


def swap_delta(trace: Trace, rank: int = 0, stage: int = 0) -> Trace:
    """Wrong-direction mutant: one rank pushes its buffer the wrong
    way at one stage — the rank it should have fed waits on a
    delivery that never comes."""
    t = copy_trace(trace)
    evs = t.events[rank]
    # The swapped send goes to the rank's *source* partner (whoever it
    # receives from at this stage) instead of its destination.
    wrong = next(e.key[2] for e in evs
                 if e.kind == "consume" and e.call == stage)
    for i, e in enumerate(evs):
        if e.kind == "signal" and e.call == stage and \
                e.sem is not None and e.sem[0] == "p2p":
            evs[i] = dataclasses.replace(e, dst=wrong)
    return t
