"""Annotation-coverage pass: every fused-op invocation executes under
a ``device.<op>.*`` profiler label the devprof parser can attribute.

``obs.devprof`` (docs/observability.md "Device-time truth") keys its
MEASURED per-op attribution on the ``TraceAnnotation`` labels the
resilience router plants around each ``@resilient`` invocation
(``device.<op>.<branch>``) and the serving pump sampler plants around
a profiled iteration (``device.step``). Those labels are load-bearing:
strip one and the parser does not fail — it silently books the op's
device time as ``device.unlabeled_ms`` and every
``*_overlap_pct_measured`` number quietly reads from an empty window.
This pass makes that failure mode a CI error instead of a silent
mis-attribution:

- ``devprof.unlabeled`` — the router's per-invocation binder
  (``call`` inside :func:`resilient`) no longer wraps the entry
  invocation in an annotate call whose label starts with
  ``device.`` (mutation test: strip the ``with`` → this finding).
- ``devprof.step_unlabeled`` — the pump sampler's iteration wrapper
  no longer plants :data:`obs.devprof.STEP_LABEL`, or the scheduler
  pump no longer routes its engine work through ``.iteration()``.
- ``devprof.bad_op_label`` — a ``@resilient`` op name contains a dot,
  which would corrupt the ``device.<op>.*`` metric prefix the parser
  derives from label segment 2.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["check_router", "check_sampler", "collect_resilient_ops",
           "run"]

_ANNOTATE_NAMES = ("annotate", "_op_annotation", "TraceAnnotation")


def _is_device_annotate(call: ast.Call) -> bool:
    """Does ``call`` produce a ``device.``-prefixed profiler label?

    Accepts ``annotate(f"device.{...}")`` directly and the router's
    ``_op_annotation(op, ...)`` helper (whose own body is checked for
    the literal prefix by :func:`check_router`)."""
    name = call.func.attr if isinstance(call.func, ast.Attribute) \
        else getattr(call.func, "id", None)
    if name not in _ANNOTATE_NAMES:
        return False
    if name == "_op_annotation":
        return True      # prefix verified at the helper's definition
    if not call.args:
        return False
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value.startswith("device.")
    if isinstance(a, ast.JoinedStr) and a.values:
        first = a.values[0]
        return (isinstance(first, ast.Constant)
                and str(first.value).startswith("device."))
    if isinstance(a, ast.Name):
        return a.id in ("STEP_LABEL",)
    if isinstance(a, ast.Attribute):
        return a.attr in ("STEP_LABEL",)
    return False


def _invocation_labeled(fn: ast.FunctionDef, invoke_pred) -> bool:
    """Is every call matching ``invoke_pred`` inside ``fn`` lexically
    under a ``with`` whose items include a device-label annotation?"""
    hits = [False]

    def walk(node, labeled):
        if isinstance(node, ast.With):
            items_labeled = labeled or any(
                isinstance(i.context_expr, ast.Call)
                and _is_device_annotate(i.context_expr)
                for i in node.items)
            for child in node.body:
                walk(child, items_labeled)
            for i in node.items:
                walk(i.context_expr, labeled)
            return
        if isinstance(node, ast.Call) and invoke_pred(node):
            hits[0] = True
            if not labeled:
                raise _Unlabeled(node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child, labeled)

    class _Unlabeled(Exception):
        def __init__(self, lineno):
            self.lineno = lineno

    try:
        for stmt in fn.body:
            walk(stmt, False)
    except _Unlabeled:
        return False
    return hits[0]


def _helper_has_device_prefix(tree: ast.Module) -> bool:
    """``_op_annotation``'s body builds a literal ``device.``-prefixed
    label (the indirection :func:`_is_device_annotate` trusts)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_op_annotation":
            for sub in ast.walk(node):
                if isinstance(sub, ast.JoinedStr) and sub.values:
                    first = sub.values[0]
                    if isinstance(first, ast.Constant) \
                            and str(first.value).startswith("device."):
                        return True
            return False
    return False


def check_router(router_path) -> list[Finding]:
    """The router's per-invocation binder wraps the entry call in a
    ``device.<op>.*`` annotation."""
    router_path = Path(router_path)
    try:
        tree = ast.parse(router_path.read_text(),
                         filename=str(router_path))
    except (OSError, SyntaxError) as e:
        return [Finding(
            code="devprof.unlabeled", severity="error",
            message=f"cannot parse {router_path}: {e}",
            file=str(router_path), pass_name="annotation-coverage")]
    findings: list[Finding] = []

    def is_entry_invocation(call: ast.Call) -> bool:
        # The binder re-invokes the wrapped entry as fn(*b.args,
        # **b.kwargs) — a Starred call of the closed-over `fn`.
        return (isinstance(call.func, ast.Name)
                and call.func.id == "fn"
                and any(isinstance(a, ast.Starred) for a in call.args))

    binders = [node for node in ast.walk(tree)
               if isinstance(node, ast.FunctionDef)
               and node.name == "call"]
    helper_ok = _helper_has_device_prefix(tree)
    labeled = any(_invocation_labeled(b, is_entry_invocation)
                  for b in binders) and helper_ok
    if not binders or not labeled:
        anchor = binders[0].lineno if binders else None
        findings.append(Finding(
            code="devprof.unlabeled",
            message="the @resilient invocation binder no longer runs "
                    "the entry under a device.<op>.* profiler "
                    "annotation — obs.devprof will attribute every "
                    "fused op's device time to device.unlabeled_ms "
                    "and *_overlap_pct_measured reads empty windows",
            file=str(router_path), line=anchor,
            pass_name="annotation-coverage",
            fix_hint="wrap the fn(*b.args, **b.kwargs) invocation in "
                     "_op_annotation(op, impl, fallback_impl) (an "
                     "annotate(f'device.{op}.<branch>') context)"))
    return findings


def check_sampler(devprof_path, scheduler_path) -> list[Finding]:
    """The pump sampler plants STEP_LABEL and the scheduler routes its
    engine work through ``.iteration()``."""
    findings: list[Finding] = []
    devprof_path, scheduler_path = Path(devprof_path), Path(scheduler_path)
    try:
        dev_src = devprof_path.read_text()
        sched_src = scheduler_path.read_text()
    except OSError as e:
        return [Finding(
            code="devprof.step_unlabeled", severity="error",
            message=f"cannot read sampler sources: {e}",
            file=str(devprof_path), pass_name="annotation-coverage")]
    if not re.search(r'STEP_LABEL\s*=\s*["\']device\.step["\']',
                     dev_src) \
            or not re.search(r"annotate\(STEP_LABEL\)", dev_src):
        findings.append(Finding(
            code="devprof.step_unlabeled",
            message="obs/devprof.py no longer annotates profiled pump "
                    "iterations with STEP_LABEL='device.step' — "
                    "device.step.* gauges will read empty windows",
            file=str(devprof_path), line=1,
            pass_name="annotation-coverage",
            fix_hint="keep STEP_LABEL='device.step' and the "
                     "annotate(STEP_LABEL) wrapper in "
                     "PumpSampler.iteration"))
    if ".iteration()" not in sched_src:
        findings.append(Finding(
            code="devprof.step_unlabeled",
            message="serving/scheduler.py pump no longer wraps its "
                    "engine work in the devprof sampler's "
                    ".iteration() window",
            file=str(scheduler_path), line=1,
            pass_name="annotation-coverage",
            fix_hint="wrap the lock-free engine-work region of "
                     "_pump_loop in self.devprof.iteration()"))
    return findings


_RESILIENT_DECOR = re.compile(r"^\s*@resilient\(\s*[\"']([^\"']+)[\"']",
                              re.MULTILINE)


def collect_resilient_ops(ops_dir) -> list[tuple[str, str, int]]:
    """(op, file, line) for every ``@resilient("op")`` decorator."""
    out = []
    for py in sorted(Path(ops_dir).glob("*.py")):
        text = py.read_text()
        for m in _RESILIENT_DECOR.finditer(text):
            line = text[:m.start()].count("\n") + 1
            out.append((m.group(1), str(py), line))
    return out


def run(root=None) -> list[Finding]:
    if root is None:
        import triton_dist_tpu
        root = Path(triton_dist_tpu.__file__).parent.parent
    root = Path(root)
    pkg = root / "triton_dist_tpu"
    findings = check_router(pkg / "resilience" / "router.py")
    findings += check_sampler(pkg / "obs" / "devprof.py",
                              pkg / "serving" / "scheduler.py")
    for op, file, line in collect_resilient_ops(pkg / "ops"):
        if "." in op:
            findings.append(Finding(
                code="devprof.bad_op_label",
                message=f"@resilient op name {op!r} contains a dot — "
                        f"the device.<op>.* label/metric prefix "
                        f"becomes ambiguous to the devprof parser",
                file=file, line=line, pass_name="annotation-coverage",
                fix_hint="use a dot-free op name"))
    return findings
