"""Annotation-coverage pass: every fused-op invocation executes under
a ``device.<op>.*`` profiler label the devprof parser can attribute.

``obs.devprof`` (docs/observability.md "Device-time truth") keys its
MEASURED per-op attribution on the ``TraceAnnotation`` labels the
resilience router plants around each ``@resilient`` invocation
(``device.<op>.<branch>``) and the serving pump sampler plants around
a profiled iteration (``device.step``). Those labels are load-bearing:
strip one and the parser does not fail — it silently books the op's
device time as ``device.unlabeled_ms`` and every
``*_overlap_pct_measured`` number quietly reads from an empty window.
This pass makes that failure mode a CI error instead of a silent
mis-attribution:

- ``devprof.unlabeled`` — the router's per-invocation binder
  (``call`` inside :func:`resilient`) no longer wraps the entry
  invocation in an annotate call whose label starts with
  ``device.`` (mutation test: strip the ``with`` → this finding).
- ``devprof.step_unlabeled`` — the pump sampler's iteration wrapper
  no longer plants :data:`obs.devprof.STEP_LABEL` (directly or via
  ``step_label()``), or the scheduler pump no longer routes its
  engine work through ``.iteration()``.
- ``devprof.step_path_blended`` — the per-decode-path step labels
  degraded: ``step_label("mega")`` no longer yields
  ``device.step.mega``, ``summarize`` blends ``device.step.mega`` /
  ``device.step.plain`` windows into one ``step`` op (checked
  BEHAVIORALLY, on synthetic events, against the file under lint), or
  the scheduler stopped bracketing the shared decode step with
  ``annotate(devprof.step_label(kind))``. Any of these silently hands
  the auto decode-path policy (``Engine(decode_path="auto")``) a
  blended or empty gauge to arbitrate on — mutation tests strip each
  in turn.
- ``devprof.bad_op_label`` — a ``@resilient`` op name contains a dot,
  which would corrupt the ``device.<op>.*`` metric prefix the parser
  derives from label segment 2.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["check_router", "check_sampler", "collect_resilient_ops",
           "run"]

_ANNOTATE_NAMES = ("annotate", "_op_annotation", "TraceAnnotation")


def _is_device_annotate(call: ast.Call) -> bool:
    """Does ``call`` produce a ``device.``-prefixed profiler label?

    Accepts ``annotate(f"device.{...}")`` directly and the router's
    ``_op_annotation(op, ...)`` helper (whose own body is checked for
    the literal prefix by :func:`check_router`)."""
    name = call.func.attr if isinstance(call.func, ast.Attribute) \
        else getattr(call.func, "id", None)
    if name not in _ANNOTATE_NAMES:
        return False
    if name == "_op_annotation":
        return True      # prefix verified at the helper's definition
    if not call.args:
        return False
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, str):
        return a.value.startswith("device.")
    if isinstance(a, ast.JoinedStr) and a.values:
        first = a.values[0]
        return (isinstance(first, ast.Constant)
                and str(first.value).startswith("device."))
    if isinstance(a, ast.Name):
        return a.id in ("STEP_LABEL",)
    if isinstance(a, ast.Attribute):
        return a.attr in ("STEP_LABEL",)
    return False


def _invocation_labeled(fn: ast.FunctionDef, invoke_pred) -> bool:
    """Is every call matching ``invoke_pred`` inside ``fn`` lexically
    under a ``with`` whose items include a device-label annotation?"""
    hits = [False]

    def walk(node, labeled):
        if isinstance(node, ast.With):
            items_labeled = labeled or any(
                isinstance(i.context_expr, ast.Call)
                and _is_device_annotate(i.context_expr)
                for i in node.items)
            for child in node.body:
                walk(child, items_labeled)
            for i in node.items:
                walk(i.context_expr, labeled)
            return
        if isinstance(node, ast.Call) and invoke_pred(node):
            hits[0] = True
            if not labeled:
                raise _Unlabeled(node.lineno)
        for child in ast.iter_child_nodes(node):
            walk(child, labeled)

    class _Unlabeled(Exception):
        def __init__(self, lineno):
            self.lineno = lineno

    try:
        for stmt in fn.body:
            walk(stmt, False)
    except _Unlabeled:
        return False
    return hits[0]


def _helper_has_device_prefix(tree: ast.Module) -> bool:
    """``_op_annotation``'s body builds a literal ``device.``-prefixed
    label (the indirection :func:`_is_device_annotate` trusts)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_op_annotation":
            for sub in ast.walk(node):
                if isinstance(sub, ast.JoinedStr) and sub.values:
                    first = sub.values[0]
                    if isinstance(first, ast.Constant) \
                            and str(first.value).startswith("device."):
                        return True
            return False
    return False


def check_router(router_path) -> list[Finding]:
    """The router's per-invocation binder wraps the entry call in a
    ``device.<op>.*`` annotation."""
    router_path = Path(router_path)
    try:
        tree = ast.parse(router_path.read_text(),
                         filename=str(router_path))
    except (OSError, SyntaxError) as e:
        return [Finding(
            code="devprof.unlabeled", severity="error",
            message=f"cannot parse {router_path}: {e}",
            file=str(router_path), pass_name="annotation-coverage")]
    findings: list[Finding] = []

    def is_entry_invocation(call: ast.Call) -> bool:
        # The binder re-invokes the wrapped entry as fn(*b.args,
        # **b.kwargs) — a Starred call of the closed-over `fn`.
        return (isinstance(call.func, ast.Name)
                and call.func.id == "fn"
                and any(isinstance(a, ast.Starred) for a in call.args))

    binders = [node for node in ast.walk(tree)
               if isinstance(node, ast.FunctionDef)
               and node.name == "call"]
    helper_ok = _helper_has_device_prefix(tree)
    labeled = any(_invocation_labeled(b, is_entry_invocation)
                  for b in binders) and helper_ok
    if not binders or not labeled:
        anchor = binders[0].lineno if binders else None
        findings.append(Finding(
            code="devprof.unlabeled",
            message="the @resilient invocation binder no longer runs "
                    "the entry under a device.<op>.* profiler "
                    "annotation — obs.devprof will attribute every "
                    "fused op's device time to device.unlabeled_ms "
                    "and *_overlap_pct_measured reads empty windows",
            file=str(router_path), line=anchor,
            pass_name="annotation-coverage",
            fix_hint="wrap the fn(*b.args, **b.kwargs) invocation in "
                     "_op_annotation(op, impl, fallback_impl) (an "
                     "annotate(f'device.{op}.<branch>') context)"))
    return findings


def check_sampler(devprof_path, scheduler_path) -> list[Finding]:
    """The pump sampler plants STEP_LABEL and the scheduler routes its
    engine work through ``.iteration()``."""
    findings: list[Finding] = []
    devprof_path, scheduler_path = Path(devprof_path), Path(scheduler_path)
    try:
        dev_src = devprof_path.read_text()
        sched_src = scheduler_path.read_text()
    except OSError as e:
        return [Finding(
            code="devprof.step_unlabeled", severity="error",
            message=f"cannot read sampler sources: {e}",
            file=str(devprof_path), pass_name="annotation-coverage")]
    if not re.search(r'STEP_LABEL\s*=\s*["\']device\.step["\']',
                     dev_src) \
            or not re.search(r"annotate\((?:STEP_LABEL\)|step_label\()",
                             dev_src):
        findings.append(Finding(
            code="devprof.step_unlabeled",
            message="obs/devprof.py no longer annotates profiled pump "
                    "iterations with STEP_LABEL='device.step' — "
                    "device.step.* gauges will read empty windows",
            file=str(devprof_path), line=1,
            pass_name="annotation-coverage",
            fix_hint="keep STEP_LABEL='device.step' and the "
                     "annotate(STEP_LABEL) wrapper in "
                     "PumpSampler.iteration"))
    if ".iteration(" not in sched_src:
        findings.append(Finding(
            code="devprof.step_unlabeled",
            message="serving/scheduler.py pump no longer wraps its "
                    "engine work in the devprof sampler's "
                    ".iteration() window",
            file=str(scheduler_path), line=1,
            pass_name="annotation-coverage",
            fix_hint="wrap the lock-free engine-work region of "
                     "_pump_loop in self.devprof.iteration()"))
    findings += _check_step_paths(devprof_path, scheduler_path,
                                  dev_src, sched_src)
    return findings


#: Synthetic capture used for the BEHAVIORAL step-path check: one exec
#: event inside a ``device.step.mega`` window, one inside a
#: ``device.step.plain`` window. A correct parser attributes them to
#: two distinct ops; a blending mutant books both under ``step``.
_STEP_PATH_EVENTS = [
    {"name": "device.step.mega", "ts_us": 0.0, "dur_us": 100.0,
     "pid": 1, "tid": 1, "device": False},
    {"name": "fusion.exec", "ts_us": 10.0, "dur_us": 50.0,
     "pid": 2, "tid": 1, "device": True},
    {"name": "device.step.plain", "ts_us": 200.0, "dur_us": 100.0,
     "pid": 1, "tid": 1, "device": False},
    {"name": "fusion.exec", "ts_us": 210.0, "dur_us": 50.0,
     "pid": 2, "tid": 1, "device": True},
]


def _check_step_paths(devprof_path, scheduler_path, dev_src,
                      sched_src) -> list[Finding]:
    """The per-decode-path step attribution holds end to end: the
    label builder, the parser (run on synthetic events — a behavioral
    check, so a rewrite that regexes clean but still blends fails),
    and the scheduler's path naming."""
    findings: list[Finding] = []

    def blended(msg: str, path, fix: str) -> Finding:
        return Finding(
            code="devprof.step_path_blended", message=msg,
            file=str(path), line=1, pass_name="annotation-coverage",
            fix_hint=fix)

    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "_tdt_lint_devprof", devprof_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        lbl = mod.step_label("mega")
        ops = mod.summarize(list(_STEP_PATH_EVENTS))["ops"]
        ok = (lbl == "device.step.mega"
              and "step.mega" in ops and "step.plain" in ops
              and "step" not in ops)
    except Exception as e:  # noqa: BLE001 — an unloadable file fails
        findings.append(blended(
            f"cannot evaluate obs/devprof.py step-path attribution: "
            f"{e!r}", devprof_path,
            "keep step_label() and summarize() importable"))
        return findings
    if not ok:
        findings.append(blended(
            "obs/devprof.py no longer attributes device.step.mega / "
            "device.step.plain windows to separate step.<kind> ops — "
            "the auto decode-path policy would arbitrate on a blended "
            "(or empty) device.step gauge",
            devprof_path,
            "keep step_label(kind) -> f'{STEP_LABEL}.{kind}' and the "
            "step two-segment rule in _label_op/summarize"))
    if not re.search(r"annotate\(\s*devprof\.step_label\(", sched_src):
        findings.append(blended(
            "serving/scheduler.py no longer brackets the shared "
            "decode step with the per-path devprof.step_label(kind) "
            "annotation — mega and plain decode steps would blend "
            "into the whole-iteration device.step window (admission/"
            "prefill contamination included)", scheduler_path,
            "wrap the sess.decode_step() call in "
            "annotate(devprof.step_label(kind)) while a capture is "
            "open"))
    return findings


_RESILIENT_DECOR = re.compile(r"^\s*@resilient\(\s*[\"']([^\"']+)[\"']",
                              re.MULTILINE)


def collect_resilient_ops(ops_dir) -> list[tuple[str, str, int]]:
    """(op, file, line) for every ``@resilient("op")`` decorator."""
    out = []
    for py in sorted(Path(ops_dir).glob("*.py")):
        text = py.read_text()
        for m in _RESILIENT_DECOR.finditer(text):
            line = text[:m.start()].count("\n") + 1
            out.append((m.group(1), str(py), line))
    return out


def run(root=None) -> list[Finding]:
    if root is None:
        import triton_dist_tpu
        root = Path(triton_dist_tpu.__file__).parent.parent
    root = Path(root)
    pkg = root / "triton_dist_tpu"
    findings = check_router(pkg / "resilience" / "router.py")
    findings += check_sampler(pkg / "obs" / "devprof.py",
                              pkg / "serving" / "scheduler.py")
    for op, file, line in collect_resilient_ops(pkg / "ops"):
        if "." in op:
            findings.append(Finding(
                code="devprof.bad_op_label",
                message=f"@resilient op name {op!r} contains a dot — "
                        f"the device.<op>.* label/metric prefix "
                        f"becomes ambiguous to the devprof parser",
                file=file, line=line, pass_name="annotation-coverage",
                fix_hint="use a dot-free op name"))
    return findings
