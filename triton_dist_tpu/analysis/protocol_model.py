"""Reusable symbolic protocol-model core for the comm-kernel zoo.

Extracted from the ring checker (``analysis/ring_model.py``, PR 8) so
every signal/wait protocol in ``ops/`` — the fused-GEMM rings, the EP
all-to-all's slab/chunk push, the PP ``_shift_kernel`` hops, the
flash-decode softmax-state combine — shares one verified execution
model instead of growing a private checker each (ISSUE 12; the
``protocol-coverage`` meta-lint in :mod:`.lint_protocol` enforces that
every semaphore-using kernel is claimed by *some* pass built on this
core).

The model: each kernel schedule is mirrored into per-rank **event
traces** over four event kinds —

- ``signal``: a remote-copy start (or remote ``semaphore_signal``)
  whose recv side of ``sem`` fires at ``dst`` and whose send side
  fires back at the source;
- ``wait_recv`` / ``wait_send``: blocking decrements of the local
  side of ``sem``;
- ``consume``: a read of data tile ``key`` guarded by delivery
  semaphore ``guard`` (``None`` = local data).

Verdicts (:func:`check_trace`, codes prefixed by
``Trace.code_prefix`` so each protocol family owns distinct finding
classes):

- ``<p>.deadlock`` — greedy maximal execution leaves a rank blocked.
  Waits are the only blocking ops and signals are monotonic (each
  (dst, sem) counter only grows), so the maximal execution is
  *unique*: any rank blocked there is deadlocked under every
  interleaving.
- ``<p>.signal_wait_imbalance`` — signals vs waits per (rank, sem),
  both recv and send sides (a surplus leaves a semaphore nonzero at
  kernel exit; a deficit is a hang).
- ``<p>.race`` — a consume of a remote tile with no prior wait on its
  delivery semaphore in program order (the static analog of
  ``TDT_DETECT_RACES``).
- ``<p>.coverage`` — consume counts differ from the trace's expected
  map (a tile landing twice, or never).

Cross-call composition: traces compose by per-rank concatenation
(:func:`concat_traces`), events optionally stamped with their call
index (``Ev.call``) so protocol-specific invariants — e.g. the
all-to-all double-buffer call-parity re-expression
(:mod:`.a2a_model`) — can be checked across call sequences.
``barrier_evs`` models ``dl.barrier_all`` (world signals + a
world-count wait per rank) so composed traces carry the same
inter-call ordering the kernels rely on.
"""

from __future__ import annotations

import dataclasses
import inspect
from collections import Counter

from triton_dist_tpu.analysis.findings import Finding

__all__ = [
    "Ev", "Trace", "Violation", "check_trace", "concat_traces",
    "barrier_evs", "anchor_of", "violations_to_findings",
    "drop_first_wait", "double_signal", "copy_trace", "first_event",
]


@dataclasses.dataclass(frozen=True)
class Ev:
    """One protocol event in a rank's program order.

    ``signal``: a remote-copy start at ``rank`` whose recv semaphore
    ``sem`` fires at ``dst`` (and whose send semaphore fires back at
    ``rank``). ``wait_recv``/``wait_send``: blocking decrements of the
    local side of ``sem``. ``consume``: a read of output-tile ``key``
    guarded by delivery semaphore ``guard`` (``None`` = local data).
    ``call`` stamps the event's call index in a composed multi-call
    trace (``None`` for single-call traces).
    """
    kind: str
    rank: int
    sem: tuple | None = None
    dst: int | None = None
    key: tuple | None = None
    guard: tuple | None = None
    call: int | None = None


@dataclasses.dataclass
class Trace:
    """Per-rank event lists for one kernel schedule, plus the coverage
    oracle (``expected`` consume keys per rank; ``outputs`` are
    symbolic reduction results as (rank, unit, {chunk: contributors})
    tuples — see :func:`check_trace`). ``code_prefix`` namespaces the
    violation codes (``ring.*``, ``a2a.*``, ``p2p.*``, ``flash.*``)."""
    name: str
    world: int
    dirs: int
    events: dict
    expected: dict
    outputs: list = dataclasses.field(default_factory=list)
    anchor: tuple = (None, None)
    code_prefix: str = "ring"


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str       # <prefix>.deadlock / <prefix>.signal_wait_imbalance
    #                 / <prefix>.race / <prefix>.coverage / ...
    detail: str


def anchor_of(obj) -> tuple:
    """(file, line) of the kernel/helper a trace mirrors — the code a
    finding asks you to change."""
    try:
        file = inspect.getsourcefile(obj)
        _, line = inspect.getsourcelines(obj)
        return file, line
    except (OSError, TypeError):
        return None, None


def barrier_evs(me: int, world: int, tag) -> list:
    """Events mirroring ``dl.barrier_all``: signal every rank
    (including self, keeping the count uniform) on the barrier
    semaphore, then wait for world-many signals. ``tag`` namespaces
    the barrier instance (e.g. the call index in a composed trace —
    each ``pallas_call``'s barrier epoch)."""
    evs = [Ev("signal", me, sem=("bar", tag), dst=d)
           for d in range(world)]
    evs.extend([Ev("wait_recv", me, sem=("bar", tag))] * world)
    return evs


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

def check_trace(trace: Trace) -> list:
    """All protocol violations in one trace (empty list == verified)."""
    p = trace.code_prefix
    v: list[Violation] = []
    events = trace.events

    # --- deadlock: greedy maximal execution -------------------------------
    # Waits are the only blocking ops and signals are monotonic (each
    # (dst, sem) counter only grows), so running every rank as far as
    # it can, repeatedly, reaches THE unique maximal execution: any
    # rank still blocked there is deadlocked under every schedule.
    pos = {r: 0 for r in events}
    sig_recv: Counter = Counter()   # (dst, sem) -> signals executed
    sig_send: Counter = Counter()   # (src, sem)
    got_recv: Counter = Counter()
    got_send: Counter = Counter()
    progress = True
    while progress:
        progress = False
        for r, evs in events.items():
            while pos[r] < len(evs):
                e = evs[pos[r]]
                if e.kind == "signal":
                    sig_recv[(e.dst, e.sem)] += 1
                    sig_send[(r, e.sem)] += 1
                elif e.kind == "wait_recv":
                    if got_recv[(r, e.sem)] >= sig_recv[(r, e.sem)]:
                        break
                    got_recv[(r, e.sem)] += 1
                elif e.kind == "wait_send":
                    if got_send[(r, e.sem)] >= sig_send[(r, e.sem)]:
                        break
                    got_send[(r, e.sem)] += 1
                pos[r] += 1
                progress = True
    stuck = {r: events[r][pos[r]] for r in events
             if pos[r] < len(events[r])}
    if stuck:
        blocked = ", ".join(
            f"rank {r} blocked in {e.kind} on sem {e.sem}"
            for r, e in sorted(stuck.items()))
        v.append(Violation(
            f"{p}.deadlock",
            f"{trace.name}: wait-before-signal cycle — {blocked}"))

    # --- signal/wait balance (full traces, independent of execution) ------
    want_recv: Counter = Counter()
    want_send: Counter = Counter()
    have_recv: Counter = Counter()
    have_send: Counter = Counter()
    for r, evs in events.items():
        for e in evs:
            if e.kind == "signal":
                have_recv[(e.dst, e.sem)] += 1
                have_send[(r, e.sem)] += 1
            elif e.kind == "wait_recv":
                want_recv[(r, e.sem)] += 1
            elif e.kind == "wait_send":
                want_send[(r, e.sem)] += 1
    for side, have, want in (("recv", have_recv, want_recv),
                             ("send", have_send, want_send)):
        for key in sorted(set(have) | set(want), key=repr):
            if key[1] and key[1][0] == "bar" and side == "send":
                continue   # barrier signals have no send-side wait
            if have[key] != want[key]:
                rank, sem = key
                v.append(Violation(
                    f"{p}.signal_wait_imbalance",
                    f"{trace.name}: sem {sem} at rank {rank}: "
                    f"{have[key]} signal(s) vs {want[key]} "
                    f"wait_{side}(s)"))

    # --- arrival ordering (the static analog of TDT_DETECT_RACES) --------
    for r, evs in events.items():
        waited: set = set()
        for e in evs:
            if e.kind == "wait_recv":
                waited.add(e.sem)
            elif e.kind == "consume" and e.guard is not None \
                    and e.guard not in waited:
                v.append(Violation(
                    f"{p}.race",
                    f"{trace.name}: rank {r} consumes {e.key} before "
                    f"any wait on its delivery sem {e.guard} "
                    f"(read of an in-flight chunk)"))

    # --- chunk-coverage exactness -----------------------------------------
    for r, evs in events.items():
        seen = Counter(e.key for e in evs if e.kind == "consume")
        want = trace.expected.get(r, {})
        for key in sorted(set(seen) | set(want), key=repr):
            if seen[key] != want.get(key, 0):
                v.append(Violation(
                    f"{p}.coverage",
                    f"{trace.name}: rank {r} consumes tile {key} "
                    f"{seen[key]}x (expected {want.get(key, 0)}x)"))
    all_ranks = tuple(range(trace.world))
    for rank, unit, value in trace.outputs:
        if set(value) != {rank} or \
                tuple(sorted(value.get(rank, ()))) != all_ranks:
            v.append(Violation(
                f"{p}.coverage",
                f"{trace.name}: output chunk {rank} (col unit {unit}) "
                f"reduces {value!r}, want every rank's partial of "
                f"chunk {rank} exactly once"))
    return v


def concat_traces(traces: list, name: str) -> Trace:
    """Compose consecutive calls into one trace by per-rank
    concatenation in call order — the model of a host issuing the same
    kernel repeatedly. Expected-consume maps merge by summation (a
    chunk live in two calls must land twice); semaphore namespacing
    across calls is the *builders'* job (fresh per-call tuples model
    per-``pallas_call`` scratch semaphores; shared tuples model
    persistent symmetric buffers, the reference's parity regime)."""
    assert traces, "nothing to compose"
    world = traces[0].world
    events: dict = {r: [] for r in range(world)}
    expected: dict = {r: Counter() for r in range(world)}
    outputs: list = []
    for t in traces:
        assert t.world == world
        for r in range(world):
            events[r].extend(t.events.get(r, ()))
            expected[r].update(t.expected.get(r, {}))
        outputs.extend(t.outputs)
    return Trace(name=name, world=world, dirs=traces[0].dirs,
                 events=events,
                 expected={r: dict(c) for r, c in expected.items()},
                 outputs=outputs, anchor=traces[0].anchor,
                 code_prefix=traces[0].code_prefix)


def violations_to_findings(trace: Trace, pass_name: str,
                           fix_hint: str = "",
                           violations: list | None = None) -> list:
    """Wrap a trace's violations as findings anchored at the kernel
    the trace mirrors — the one construction every protocol pass
    shares. ``violations`` defaults to :func:`check_trace`; passes
    with extra structural verdicts (the a2a parity check) pass the
    combined list in."""
    if violations is None:
        violations = check_trace(trace)
    file, line = trace.anchor
    return [Finding(code=v.code, message=v.detail, file=file, line=line,
                    pass_name=pass_name, fix_hint=fix_hint)
            for v in violations]


# ---------------------------------------------------------------------------
# Generic mutators (tests/test_tdt_check.py, tests/test_protocol_check
# .py): known-bad schedule mutants. Each returns a NEW trace; a checker
# that passes all of them is untested.
# ---------------------------------------------------------------------------

def copy_trace(trace: Trace) -> Trace:
    return dataclasses.replace(
        trace, events={r: list(evs) for r, evs in trace.events.items()},
        expected={r: dict(x) for r, x in trace.expected.items()},
        outputs=list(trace.outputs), name=trace.name + "+mut")


def first_event(trace: Trace, kind: str, rank=None,
                sem_kind: str | None = None) -> tuple:
    """(rank, index) of the first event of ``kind`` (optionally
    restricted to one rank, or to sems whose leading tag matches
    ``sem_kind`` — so mutators can skip barrier events)."""
    for r in sorted(trace.events):
        if rank is not None and r != rank:
            continue
        for i, e in enumerate(trace.events[r]):
            if e.kind != kind:
                continue
            if sem_kind is not None and \
                    (e.sem is None or e.sem[0] != sem_kind):
                continue
            return r, i
    raise ValueError(f"no {kind} event in {trace.name}")


def drop_first_wait(trace: Trace, rank=None,
                    sem_kind: str | None = None) -> Trace:
    """Dropped-wait mutant: a chunk is read while still in flight."""
    t = copy_trace(trace)
    r, i = first_event(t, "wait_recv", rank, sem_kind)
    del t.events[r][i]
    return t


def double_signal(trace: Trace, rank=None,
                  sem_kind: str | None = None) -> Trace:
    """Doubled-signal mutant: a semaphore is left nonzero at exit."""
    t = copy_trace(trace)
    r, i = first_event(t, "signal", rank, sem_kind)
    t.events[r].insert(i, t.events[r][i])
    return t
