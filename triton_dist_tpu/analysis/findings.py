"""Findings model for the static-analysis framework (docs/analysis.md).

A :class:`Finding` is one defect a pass surfaced: a stable ``code``
(the finding class mutation tests assert on), a severity, a
``file:line`` anchor pointing at the code that must change, and a fix
hint. Passes return lists of findings; the driver
(``tools/tdt_check.py``) renders them human- or JSON-side and exits
nonzero when any ``error`` survives suppression.

Suppression is inline and anchored: a ``# tdt: ignore[<code>]``
pragma on the flagged line (or ``# tdt: ignore`` for any code) drops
the finding — the pragma lives next to the code it excuses, so a
suppression can never outlive its reason invisibly.
"""

from __future__ import annotations

import dataclasses
import json
import re

__all__ = ["Finding", "SEVERITIES", "filter_suppressed", "render_human",
           "render_json", "exit_code"]

SEVERITIES = ("error", "warning")

#: ``# tdt: ignore`` or ``# tdt: ignore[code, other.code]``
_PRAGMA = re.compile(r"#\s*tdt:\s*ignore(?:\[([^\]]*)\])?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect surfaced by a pass.

    ``code`` is the stable finding class (``ring.deadlock``,
    ``vmem.over_budget``, ``lint.metric_undocumented``, ...) —
    mutation tests and suppression pragmas key on it, so renaming one
    is a breaking change to both.
    """
    code: str
    message: str
    file: str | None = None
    line: int | None = None
    severity: str = "error"
    pass_name: str = ""
    fix_hint: str = ""

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}: "
                             f"{self.severity!r}")

    @property
    def anchor(self) -> str:
        if self.file is None:
            return "<repo>"
        return f"{self.file}:{self.line}" if self.line else str(self.file)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        out = (f"{self.anchor}: {self.severity}[{self.code}] "
               f"{self.message}")
        if self.fix_hint:
            out += f"\n    fix: {self.fix_hint}"
        return out


def _suppressed_codes(line_text: str):
    """Codes suppressed by a pragma on this source line; ``None`` when
    no pragma, ``()`` for the bare catch-all form."""
    m = _PRAGMA.search(line_text)
    if m is None:
        return None
    if m.group(1) is None:
        return ()
    return tuple(c.strip() for c in m.group(1).split(",") if c.strip())


def filter_suppressed(findings, read_line=None):
    """Drop findings whose anchored source line carries a matching
    ``# tdt: ignore`` pragma. ``read_line(file, line)`` is injectable
    for tests; the default reads the file from disk (missing files /
    lines keep the finding — a suppression must be provable)."""
    if read_line is None:
        def read_line(path, lineno):
            try:
                with open(path, encoding="utf-8") as f:
                    for i, text in enumerate(f, 1):
                        if i == lineno:
                            return text
            except OSError:
                return None
            return None

    kept = []
    for f in findings:
        if f.file and f.line:
            text = read_line(f.file, f.line)
            codes = _suppressed_codes(text) if text is not None else None
            if codes is not None and (codes == () or f.code in codes):
                continue
        kept.append(f)
    return kept


def exit_code(findings) -> int:
    """Driver exit status: nonzero iff any error-severity finding."""
    return 1 if any(f.severity == "error" for f in findings) else 0


def render_human(findings, n_passes: int | None = None) -> str:
    lines = [f.render() for f in findings]
    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    suffix = f" across {n_passes} passes" if n_passes is not None else ""
    if not findings:
        lines.append(f"tdt-check OK: no findings{suffix}")
    else:
        lines.append(f"tdt-check: {n_err} error(s), {n_warn} "
                     f"warning(s){suffix}")
    return "\n".join(lines)


def render_json(findings) -> str:
    return json.dumps({"findings": [f.to_dict() for f in findings],
                       "errors": sum(1 for f in findings
                                     if f.severity == "error")},
                      indent=2, sort_keys=True)
