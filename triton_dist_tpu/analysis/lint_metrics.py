"""Metric-catalog drift pass: code and docs/observability.md agree.

The metric catalog is the contract dashboards and the report renderer
are built against; an emitted-but-undocumented metric is invisible
operational surface, and a documented-but-never-emitted one is a
dashboard reading zeros forever. This pass walks the package AST for
every ``counter``/``gauge``/``histogram`` emission (plus ``span``
calls, which record into ``<name>_ms``), normalizes f-string holes to
wildcards, and diffs both directions against the catalog table.

Dynamic names that contain no string constant at all (e.g. a name
computed in a variable) cannot be checked statically and are skipped —
keep metric names as literals or f-string templates at the emission
site so this pass can see them.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["collect_emissions", "catalog_patterns", "run"]

_EMIT_ATTRS = ("counter", "gauge", "histogram")
_PLACEHOLDER = re.compile(r"<[^<>]*>")
_BACKTICK = re.compile(r"`([^`]+)`")


def _templates(node) -> list:
    """Wildcard name templates of a metric-name argument expression.
    f-string holes become ``*``; an ``a if c else b`` of literals
    yields both; anything non-constant yields nothing (unverifiable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:
                parts.append("*")
        tpl = "".join(parts)
        return [tpl] if tpl.strip("*") else []
    if isinstance(node, ast.IfExp):
        return _templates(node.body) + _templates(node.orelse)
    return []


def collect_emissions(files) -> list:
    """(file, line, template) for every statically visible metric
    emission in ``files``."""
    out = []
    for py in files:
        try:
            tree = ast.parse(Path(py).read_text(), filename=str(py))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.args):
                continue
            attr = node.func.attr
            recv = node.func.value
            recv_name = recv.id if isinstance(recv, ast.Name) else \
                recv.attr if isinstance(recv, ast.Attribute) else None
            if attr in _EMIT_ATTRS:
                suffix = ""
            elif attr == "span" and recv_name not in ("trace",
                                                      "_trace"):
                # obs.span times into <name>_ms; trace.span is
                # timeline-only (no histogram).
                suffix = "_ms"
            else:
                continue
            for tpl in _templates(node.args[0]):
                if "." in tpl:   # every metric name is dotted
                    out.append((str(py), node.lineno, tpl + suffix))
    return out


def catalog_patterns(md_path) -> list:
    """(line, [candidate patterns]) per metric the catalog table names.

    Each backticked token in a row's metric column is one name;
    ``<placeholder>`` segments become wildcards. Suffix/alternate
    tokens (``.plain``, ``_p99_ms``, ``<name>_slow``) expand against
    the row's preceding full name at every split point sharing the
    alternate's leading character — e.g. ``.xla`` after
    ``resilience.perfwatch.samples.fused`` yields
    ``resilience.perfwatch.samples.xla`` among its candidates; a
    token matches when ANY candidate does."""
    text = Path(md_path).read_text()
    out = []
    in_catalog = False
    for lineno, line in enumerate(text.splitlines(), 1):
        if line.startswith("## "):
            in_catalog = line.strip() == "## Metric catalog"
            continue
        if not in_catalog or not line.startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 3 or set(cells[1].strip()) <= {"-", " "} \
                or cells[1].strip() == "metric":
            continue
        prev = None
        for tok in _BACKTICK.findall(cells[1]):
            pat = _PLACEHOLDER.sub("*", tok.strip())
            if not pat:
                continue
            if pat[0] not in "._*" and "." in pat:
                prev = pat
                out.append((lineno, [pat]))
                continue
            cands = ["*" + pat.lstrip("*")]
            if prev and pat[0] in "._":
                cands += [prev[:i] + pat
                          for i in range(len(prev))
                          if prev[i] == pat[0]]
            out.append((lineno, cands))
    return out


def _matches(a: str, b: str) -> bool:
    """Do two wildcard templates plausibly name the same metric?"""
    return (a == b
            or fnmatch.fnmatchcase(a.replace("*", "X"), b)
            or fnmatch.fnmatchcase(b.replace("*", "X"), a))


def run(root=None, files=None, catalog=None) -> list:
    if root is None:
        import triton_dist_tpu
        root = Path(triton_dist_tpu.__file__).parent.parent
    root = Path(root)
    if files is None:
        files = sorted((root / "triton_dist_tpu").rglob("*.py"))
    if catalog is None:
        catalog = root / "docs" / "observability.md"
    if not Path(catalog).exists():
        return [Finding(
            code="lint.metric_catalog_missing", severity="warning",
            message=f"metric catalog not found at {catalog} — "
                    f"metric-drift check skipped",
            pass_name="metric-catalog")]
    emissions = collect_emissions(files)
    patterns = catalog_patterns(catalog)
    findings = []
    for file, line, tpl in emissions:
        if not any(_matches(tpl, pat)
                   for _, cands in patterns for pat in cands):
            findings.append(Finding(
                code="lint.metric_undocumented",
                message=f"metric {tpl!r} is emitted here but missing "
                        f"from the docs/observability.md catalog",
                file=file, line=line, pass_name="metric-catalog",
                fix_hint="add a catalog row (metric | type | meaning)"))
    for line, cands in patterns:
        if not any(_matches(tpl, pat)
                   for _, _, tpl in emissions for pat in cands):
            findings.append(Finding(
                code="lint.metric_dead",
                message=f"catalog names {cands[0]!r} but no code "
                        f"emits it",
                file=str(catalog), line=line,
                pass_name="metric-catalog",
                fix_hint="drop the stale row, or restore the emission "
                         "it documented"))
    return findings
