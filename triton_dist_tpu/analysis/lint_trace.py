"""Trace-span balance pass: host-side ``trace.begin`` emitters close
what they open.

An unbalanced begin/end pair corrupts every Perfetto dump that
includes the emitter (the export validator then flags the WHOLE trace,
long after the bug merged). ``obs.span`` pairs them structurally;
anything calling ``obs.trace.begin``/``end`` by hand is checked here:
within one function the begin and end multisets (by name template)
must match — or balance across the methods of one class, the
``__enter__``/``__exit__`` shape ``obs.registry._Span`` uses.
Deliberately-unclosed spans (a hang recorder pattern) carry a
``# tdt: ignore[lint.trace_unbalanced]`` pragma at the begin site.
"""

from __future__ import annotations

import ast
from collections import Counter
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding
from triton_dist_tpu.analysis.lint_metrics import _templates

__all__ = ["run"]


def _is_trace_call(node):
    """(kind, name-template) for ``<...>trace.begin/end(...)`` calls."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("begin", "end")):
        return None
    recv = node.func.value
    recv_name = recv.id if isinstance(recv, ast.Name) else \
        recv.attr if isinstance(recv, ast.Attribute) else None
    if recv_name not in ("trace", "_trace", "tracer"):
        return None
    tpl = "*"
    if node.args:
        tpls = _templates(node.args[0])
        if tpls:
            tpl = tpls[0]
    return node.func.attr, tpl


def _counts(tree) -> tuple:
    begins: Counter = Counter()
    ends: Counter = Counter()
    first_line = {}
    for node in ast.walk(tree):
        got = _is_trace_call(node)
        if got is None:
            continue
        kind, tpl = got
        (begins if kind == "begin" else ends)[tpl] += 1
        first_line.setdefault(tpl, node.lineno)
    return begins, ends, first_line


def run(root=None, files=None) -> list:
    if root is None:
        import triton_dist_tpu
        root = Path(triton_dist_tpu.__file__).parent.parent
    root = Path(root)
    if files is None:
        files = [p for p in sorted((root / "triton_dist_tpu")
                                   .rglob("*.py"))
                 if p.name != "trace.py"]   # the emitter itself
    findings = []
    for py in files:
        try:
            tree = ast.parse(Path(py).read_text(), filename=str(py))
        except SyntaxError:
            continue
        # Scope = top-level function, or a whole class (so
        # __enter__/__exit__ pairs balance across methods).
        scopes = []
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                scopes.append(node)
        for scope in scopes:
            begins, ends, first_line = _counts(scope)
            for tpl in sorted(set(begins) | set(ends)):
                if begins[tpl] == ends[tpl]:
                    continue
                findings.append(Finding(
                    code="lint.trace_unbalanced",
                    message=f"{scope.name}: trace span {tpl!r} has "
                            f"{begins[tpl]} begin(s) vs {ends[tpl]} "
                            f"end(s)",
                    file=str(py), line=first_line[tpl],
                    pass_name="trace-balance",
                    fix_hint="close the span (or use obs.span, which "
                             "pairs begin/end structurally); a "
                             "deliberately-unclosed hang marker takes "
                             "a # tdt: ignore[lint.trace_unbalanced] "
                             "pragma"))
    return findings
