"""``protocol-coverage`` meta-lint: no comm kernel lands unverified.

The protocol passes (ring / a2a / p2p / flash-decode) each prove one
kernel family's signal/wait discipline — but nothing used to prove
the *map* stayed total: a new kernel using remote DMA semaphores
would quietly ship with no verifier claiming it, which is exactly how
the ring bugs reached a chip queue before PR 8. This lint closes the
meta-hole: it ASTs every module under ``ops/`` for semaphore/DMA
usage (``make_async_remote_copy``, ``SemaphoreType.DMA``,
``pltpu.semaphore_*``, the ``dl.*`` wrappers) and fails when a module
that uses them is claimed by no registered verifier pass — so the
NEXT comm kernel (the ROADMAP's KV-block streaming, MoE a2a variants)
cannot land unverified.

Three finding classes, all error severity:

- ``protocol.unclaimed_semaphore`` — a module uses protocol
  primitives but appears in neither :data:`CLAIMS` nor
  :data:`BACKLOG`; anchored at the first primitive usage.
- ``protocol.unknown_pass`` — a claim names a pass the registry
  doesn't have (a claim must be checkable, not a comment).
- ``protocol.stale_claim`` — a claimed/backlogged module no longer
  uses any primitive (the both-directions discipline the
  metric-catalog lint established: dead rows are drift too).

:data:`BACKLOG` enumerates the pre-zoo kernels that predate the
protocol-model core — explicit, rationale'd debt, not a licence.
Moving a module out of BACKLOG means writing its trace builder on
``analysis/protocol_model.py``; adding to it is a reviewed diff the
same way ``lint_fallback.DELEGATES`` is.

:data:`PROTOCOL_FREE` extends the map past ``ops/``: modules that sit
on comm-adjacent hot paths (the speculative-decoding machinery,
ISSUE 13) but bear NO semaphores — declared explicitly, with a
rationale, so the meta-lint says so rather than leaving it to
omission. The lint VERIFIES the claim: a protocol-free module that
grows a semaphore/DMA primitive fails with
``protocol.unclaimed_semaphore`` until a verifier pass claims it.
"""

from __future__ import annotations

import ast
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["CLAIMS", "BACKLOG", "PROTOCOL_FREE", "PRIMITIVES",
           "scan_module", "collect_findings", "run"]

#: Verified kernels: ops/ module basename -> the registered pass that
#: model-checks its protocol (docs/analysis.md pass catalog). Keys
#: containing ``/`` are PACKAGE-relative paths — comm kernels living
#: outside ops/ (the disaggregated KV-stream transport, ISSUE 18)
#: carry the same claim discipline, scanned at the package root.
CLAIMS = {
    "allgather_gemm.py": "ring-protocol",
    "gemm_reduce_scatter.py": "ring-protocol",
    "all_to_all.py": "a2a-protocol",
    "p2p.py": "p2p-protocol",
    "flash_decode.py": "flash-decode-protocol",
    "serving/kv_stream.py": "kvstream-protocol",
}

#: Pre-zoo kernels awaiting trace builders — each entry names what
#: retires it. An entry here silences the lint for that module ONLY;
#: new modules must claim a pass or extend this table in review.
BACKLOG = {
    "allgather.py": "standalone AG kernel family (ring + full-mesh "
                    "push variants); fold into ag_ring_trace shapes "
                    "next chip window (ROADMAP item 4)",
    "allreduce.py": "one-shot/ring AR staging buffers; protocol is "
                    "the gemm_rs trace's AG epilogue shape — needs "
                    "its own counts oracle",
    "reduce_scatter.py": "standalone RS ring; subsumed by "
                         "gemm_rs_trace's reduction-chain model once "
                         "the standalone schedule is mirrored",
    "group_gemm.py": "AG-side ring of the grouped-GEMM producer; "
                     "shares _make_ring structure (ring-protocol "
                     "covers the schedule, not this consumer loop)",
    "moe_reduce_rs.py": "fused MoE-RS ring (rs_copy/rs_step); "
                        "mirrors the GEMM-RS chunk protocol — trace "
                        "builder with expert-aligned coverage oracle "
                        "pending (ROADMAP item 5 MoE serving)",
    "sp_attention.py": "sequence-parallel KV ring; needs a trace "
                       "with per-(slot, dir) double-buffer oracle",
}

#: Modules OUTSIDE ops/ declared protocol-free (package-relative path
#: -> rationale). Each claim is checked, not trusted: the module is
#: scanned like any ops/ kernel, and growing a primitive fires
#: ``protocol.unclaimed_semaphore`` until a verifier pass claims it.
PROTOCOL_FREE = {
    "models/spec.py": "speculative decoding (ISSUE 13) is pure "
                      "host-side orchestration — drafters + "
                      "acceptance over jitted XLA forwards; the "
                      "widened verify step carries no semaphores. If "
                      "a fused multi-token verify kernel lands, it "
                      "claims a protocol pass here.",
}

#: Attribute names whose use marks a module as protocol-bearing.
#: ``DMA`` only counts as ``SemaphoreType.DMA``; the rest count as
#: ``pltpu.<name>`` / ``dl.<name>`` attributes or direct imports.
PRIMITIVES = frozenset({
    "make_async_remote_copy", "remote_copy", "semaphore_signal",
    "semaphore_wait", "semaphore_read", "get_barrier_semaphore",
    "barrier_all", "barrier_neighbors", "notify",
})


def scan_module(path: Path):
    """(first_line, {primitive names used}) of semaphore/DMA usage in
    one module — AST-based, so docstring prose never counts."""
    tree = ast.parse(path.read_text(encoding="utf-8"), str(path))
    used: dict = {}

    def note(name: str, node):
        used.setdefault(name, node.lineno)

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            if node.attr in PRIMITIVES:
                note(node.attr, node)
            elif node.attr == "DMA" and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "SemaphoreType":
                note("SemaphoreType.DMA", node)
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                if alias.name in PRIMITIVES:
                    note(alias.name, node)
    if not used:
        return None, frozenset()
    return min(used.values()), frozenset(used)


def collect_findings(ops_dir: Path = None, claims: dict = None,
                     backlog: dict = None, passes=None,
                     protocol_free: dict = None) -> list:
    """All protocol-coverage findings (empty == the kernel zoo map is
    total). Every input is injectable for the seeded-drift tests."""
    default_tree = ops_dir is None
    if ops_dir is None:
        import triton_dist_tpu.ops
        ops_dir = Path(triton_dist_tpu.ops.__file__).parent
    default_claims = claims is None
    claims = CLAIMS if claims is None else claims
    backlog = BACKLOG if backlog is None else backlog
    if protocol_free is None:
        # Only the real package tree carries the real protocol-free
        # map — injected ops_dirs (seeded-drift tests) opt in
        # explicitly so their synthetic trees aren't scanned for it.
        protocol_free = PROTOCOL_FREE if default_tree else {}
    if passes is None:
        from triton_dist_tpu.analysis import PASSES
        passes = PASSES
    findings = []
    seen = set()
    # "/" keys are package-relative claims (kernels outside ops/) —
    # handled in their own scan below, not by the ops/ basename walk.
    path_claims = {k: v for k, v in claims.items() if "/" in k}
    claims = {k: v for k, v in claims.items() if "/" not in k}
    if not default_tree and default_claims:
        # An injected synthetic tree with the default claims map would
        # see the real package-relative claims dangle under it — same
        # opt-in rule as PROTOCOL_FREE.
        path_claims = {}
    for path in sorted(ops_dir.glob("*.py")):
        name = path.name
        if name == "__init__.py":
            continue
        seen.add(name)
        line, used = scan_module(path)
        uses = bool(used)
        if uses and name not in claims and name not in backlog:
            findings.append(Finding(
                code="protocol.unclaimed_semaphore",
                message=f"{name} uses comm-protocol primitives "
                        f"({', '.join(sorted(used))}) but no verifier "
                        f"pass claims its protocol",
                file=str(path), line=line,
                pass_name="protocol-coverage",
                fix_hint="build a trace model on analysis/"
                         "protocol_model.py, register its pass, and "
                         "claim the module in lint_protocol.CLAIMS "
                         "(docs/analysis.md 'protocol-coverage')"))
        elif uses and name in claims and claims[name] not in passes:
            findings.append(Finding(
                code="protocol.unknown_pass",
                message=f"{name} claims verifier pass "
                        f"{claims[name]!r}, which is not registered "
                        f"— a claim must be checkable",
                file=str(path), line=line,
                pass_name="protocol-coverage",
                fix_hint="register the pass in analysis/__init__.py "
                         "or fix the CLAIMS entry"))
        elif not uses and (name in claims or name in backlog):
            findings.append(Finding(
                code="protocol.stale_claim",
                message=f"{name} is claimed"
                        f"{' (backlog)' if name in backlog else ''} "
                        f"but no longer uses any protocol primitive "
                        f"— drop the stale entry",
                file=str(path), line=1,
                pass_name="protocol-coverage",
                fix_hint="remove the module from lint_protocol."
                         f"{'BACKLOG' if name in backlog else 'CLAIMS'}"))
    for name in sorted((set(claims) | set(backlog)) - seen):
        findings.append(Finding(
            code="protocol.stale_claim",
            message=f"{name} is claimed but does not exist under "
                    f"{ops_dir}",
            file=str(ops_dir / name), line=1,
            pass_name="protocol-coverage",
            fix_hint="remove the dangling claim"))
    # Package-relative claims (comm kernels outside ops/): same three
    # finding classes as the basename walk, scanned at the package
    # root.
    pkg_dir = ops_dir.parent
    for rel in sorted(path_claims):
        path = pkg_dir / rel
        if not path.exists():
            findings.append(Finding(
                code="protocol.stale_claim",
                message=f"{rel} is claimed but does not exist under "
                        f"{pkg_dir}",
                file=str(path), line=1,
                pass_name="protocol-coverage",
                fix_hint="remove the dangling claim"))
            continue
        line, used = scan_module(path)
        if not used:
            findings.append(Finding(
                code="protocol.stale_claim",
                message=f"{rel} is claimed but no longer uses any "
                        f"protocol primitive — drop the stale entry",
                file=str(path), line=1,
                pass_name="protocol-coverage",
                fix_hint="remove the module from lint_protocol.CLAIMS"))
        elif path_claims[rel] not in passes:
            findings.append(Finding(
                code="protocol.unknown_pass",
                message=f"{rel} claims verifier pass "
                        f"{path_claims[rel]!r}, which is not "
                        f"registered — a claim must be checkable",
                file=str(path), line=line,
                pass_name="protocol-coverage",
                fix_hint="register the pass in analysis/__init__.py "
                         "or fix the CLAIMS entry"))
    # Declared protocol-free modules outside ops/ (package-relative):
    # verify the claim instead of trusting the prose.
    for rel in sorted(protocol_free):
        path = pkg_dir / rel
        if not path.exists():
            findings.append(Finding(
                code="protocol.stale_claim",
                message=f"{rel} is declared protocol-free but does "
                        f"not exist under {pkg_dir}",
                file=str(path), line=1,
                pass_name="protocol-coverage",
                fix_hint="remove the dangling PROTOCOL_FREE entry"))
            continue
        line, used = scan_module(path)
        if used:
            findings.append(Finding(
                code="protocol.unclaimed_semaphore",
                message=f"{rel} is declared protocol-free but uses "
                        f"comm-protocol primitives "
                        f"({', '.join(sorted(used))}) — the claim no "
                        f"longer holds",
                file=str(path), line=line,
                pass_name="protocol-coverage",
                fix_hint="build a trace model on analysis/"
                         "protocol_model.py, register its pass, move "
                         "the module from PROTOCOL_FREE to CLAIMS"))
    return findings


def run(root) -> list:
    del root
    return collect_findings()
