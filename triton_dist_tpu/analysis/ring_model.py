"""Static model checker for the fused GEMM family's ring protocols.

The bidirectional ring schedules (ops/common.py ``ring_chunk_schedule``,
ops/allgather_gemm.py ``_make_ring``, the GEMM-RS/AR mirrored-ring
column splits in ops/gemm_reduce_scatter.py) are signal/wait protocols
whose deadlock and race bugs only manifest on chip. This module checks
them *before* any compile: it symbolically executes the schedule —
calling the kernels' own ``ring_chunk_schedule`` / ``ring_hop_counts``
with concrete (rank, step) values, then mirroring ``_make_ring``'s
copy/wait/forward structure into explicit per-rank event traces — and
verifies, for every world size and both ``ring_dirs`` settings,
signal/wait balance, chunk-coverage exactness, deadlock freedom and
arrival ordering.

The event-trace machinery itself lives in
:mod:`.protocol_model` (shared with the a2a / p2p / flash-decode
checkers since ISSUE 12); this module keeps the ring-specific trace
builders, the ``ring.*`` finding codes, and the ring mutators. The
interpret-mode race detector checks only the (world, config) pairs a
CPU test happens to run; this checker enumerates worlds 1..8 x both
directions x every kernel schedule shape in milliseconds, so autotune
candidates no test ever executed are still vetted
(docs/analysis.md "ring-protocol").
"""

from __future__ import annotations

import dataclasses
import functools

from triton_dist_tpu.analysis.protocol_model import (
    Ev, Trace, Violation, anchor_of as _anchor_of, check_trace,
    copy_trace as _copy, double_signal, drop_first_wait,
    first_event as _first)

__all__ = [
    "Ev", "Trace", "Violation", "ag_ring_trace", "gemm_rs_trace",
    "check_trace", "family_traces", "verify_family",
    "drop_first_wait", "double_signal", "shift_consume",
    "swap_direction",
]


@functools.lru_cache(maxsize=None)
def _schedule_table(world: int, dirs: int):
    """{(me, s): (chunk, is_bwd, off)} from the REAL
    ``ring_chunk_schedule`` — the checker executes the kernels' own
    schedule code, not a reimplementation of it."""
    from triton_dist_tpu.ops.common import ring_chunk_schedule
    table = {}
    for me in range(world):
        for s in range(world):
            c, b, o = ring_chunk_schedule(me, s, world, dirs)
            table[(me, s)] = (int(c), bool(b), int(o))
    return table


@functools.lru_cache(maxsize=None)
def _hops(world: int, dirs: int):
    from triton_dist_tpu.ops.common import ring_hop_counts
    n_fwd, n_bwd = ring_hop_counts(world, dirs)
    return int(n_fwd), int(n_bwd)


def ag_ring_trace(world: int, dirs: int, m_tiles: int = 1,
                  n_blocks: int = 1) -> Trace:
    """Event trace of the fused AG-GEMM family's ring schedule.

    ``m_tiles=n_blocks=1`` mirrors the vmem kernel
    (``_ag_gemm_kernel``: consume chunk s, then ``advance(s+1)``);
    tiled shapes mirror ``_ag_gemm_hbm_nb_kernel`` (ring bookkeeping at
    chunk boundaries of N-block 0 only — later N-blocks re-read the
    workspace with no waits, safe because panel 0's waits all precede
    them in program order, which the race check verifies rather than
    assumes). The AG-SwiGLU kernel shares this exact structure
    (``_ag_swiglu_hbm_kernel`` consumes each tile twice through the
    same single arrival wait, so one consume event per tile models it).
    """
    sched = _schedule_table(world, dirs)
    n_fwd, n_bwd = _hops(world, dirs)
    tiled = (m_tiles, n_blocks) != (1, 1)
    events: dict = {}
    expected: dict = {}
    for me in range(world):
        ev: list = []
        left, right = (me - 1) % world, (me + 1) % world

        def advance(s, ev=ev, me=me, left=left, right=right):
            # mirrors _make_ring.advance: position 0 launches the local
            # chunk both ways; positions 1..world-1 wait the arrival
            # and keep it travelling while hops remain; >= world no-op.
            if world == 1:
                return
            if s == 0:
                if n_fwd:
                    ev.append(Ev("signal", me, sem=("ag", 0, me),
                                 dst=right))
                if n_bwd:
                    ev.append(Ev("signal", me, sem=("ag", 1, me),
                                 dst=left))
            elif s < world:
                chunk, is_bwd, off = sched[(me, s)]
                d = 1 if is_bwd else 0
                ev.append(Ev("wait_recv", me, sem=("ag", d, chunk)))
                if off < (n_bwd if is_bwd else n_fwd):
                    ev.append(Ev("signal", me, sem=("ag", d, chunk),
                                 dst=(left if d else right)))

        def consume(spos, mt, nb, ev=ev, me=me):
            chunk, is_bwd, _ = sched[(me, spos)] if world > 1 else \
                (me, False, 0)
            guard = None if chunk == me else \
                ("ag", 1 if is_bwd else 0, chunk)
            ev.append(Ev("consume", me, key=(chunk, mt, nb),
                         guard=guard))

        if world == 1:
            for nb in range(n_blocks):
                for mt in range(m_tiles):
                    consume(0, mt, nb)
        elif not tiled:
            advance(0)
            for s in range(world):
                consume(s, 0, 0)
                advance(s + 1)
        else:
            per_nb = world * m_tiles
            total = n_blocks * per_nb

            def ring_advance(i):
                if i < per_nb and i % m_tiles == 0:
                    advance(i // m_tiles)

            ring_advance(0)
            for i in range(total):
                ring_advance(i + 1)
                consume((i % per_nb) // m_tiles, i % m_tiles,
                        i // per_nb)
        # mirrors _make_ring.drain
        if world > 1:
            for s in range(max(n_fwd, n_bwd)):
                if s < n_fwd:
                    ev.append(Ev("wait_send", me,
                                 sem=("ag", 0, (me - s) % world)))
                if n_bwd > 0 and s < n_bwd:
                    ev.append(Ev("wait_send", me,
                                 sem=("ag", 1, (me + s) % world)))
        events[me] = ev
        expected[me] = {(c, mt, nb): 1
                        for c in range(world)
                        for mt in range(m_tiles)
                        for nb in range(n_blocks)}
    from triton_dist_tpu.ops import allgather_gemm
    return Trace(name=f"ag_ring[w{world} d{dirs} "
                      f"{m_tiles}x{n_blocks}]",
                 world=world, dirs=dirs, events=events,
                 expected=expected,
                 anchor=_anchor_of(allgather_gemm._make_ring))


def _merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for chunk, contribs in b.items():
        out[chunk] = out.get(chunk, ()) + contribs
    return out


def gemm_rs_trace(world: int, dirs: int,
                  all_gather_epilogue: bool = False,
                  send_idx_shift: int = 0) -> Trace:
    """Event trace of the GEMM-RS mirrored-ring schedule
    (``_gemm_rs_kernel``; the N-blocked kernel splits the same two
    rings over N-block ranges instead of column halves — identical
    protocol, so one trace shape covers both).

    ``dirs=2``: column half 0 reduces on the rightward ring (step s
    sends the partial for chunk me-s-1), half 1 on the mirrored
    leftward ring (chunk me+s+1). Reduction values are tracked
    symbolically as {chunk: contributor-tuple} maps so the checker can
    assert every output chunk sums every rank exactly once.
    ``all_gather_epilogue=True`` appends the GEMM-AR ring AG of the
    reduced chunks. ``send_idx_shift`` exists for mutation tests (an
    off-by-one chunk index feeds partials of the wrong shard into the
    travelling sum)."""
    cols = (0,) if dirs == 1 else (0, 1)
    events: dict = {}
    expected: dict = {}
    outputs: list = []

    def send_idx(r, d, s):
        idx = (r - s - 1) % world if d == 0 else (r + s + 1) % world
        return (idx + send_idx_shift) % world

    # Symbolic reduction chain: val[(r, d, s)] is the value rank r
    # sends at step s on ring d, as {chunk: contributors}.
    val: dict = {}
    for s in range(max(world - 1, 0)):
        for r in range(world):
            for d in cols:
                own = {send_idx(r, d, s): (r,)}
                if s == 0:
                    val[(r, d, s)] = own
                else:
                    src = (r - 1) % world if d == 0 else (r + 1) % world
                    val[(r, d, s)] = _merge(val[(src, d, s - 1)], own)

    for me in range(world):
        ev: list = []
        left, right = (me - 1) % world, (me + 1) % world
        if world == 1:
            for d in cols:
                outputs.append((me, d, {me: (me,)}))
                ev.append(Ev("consume", me, key=("out", me, d)))
            events[me] = ev
            expected[me] = {("out", me, d): 1 for d in cols}
            continue
        for s in range(world - 1):
            for d in cols:
                if s > 0:
                    ev.append(Ev("wait_recv", me, sem=("rs", d, s - 1)))
                ev.append(Ev("signal", me, sem=("rs", d, s),
                             dst=(right if d == 0 else left)))
        for d in cols:
            ev.append(Ev("wait_recv", me, sem=("rs", d, world - 2)))
            src = left if d == 0 else right
            outputs.append((me, d,
                            _merge(val[(src, d, world - 2)],
                                   {me: (me,)})))
            ev.append(Ev("consume", me, key=("out", me, d),
                         guard=("rs", d, world - 2)))
        expected[me] = {("out", me, d): 1 for d in cols}
        if all_gather_epilogue:
            # mirrors the ring AG epilogue: step s forwards the chunk
            # received at step s-1 (s=0: the locally reduced chunk) and
            # waits the next arrival.
            for s in range(world - 1):
                ev.append(Ev("signal", me,
                             sem=("arag", (me - s) % world), dst=right))
                c = (me - s - 1) % world
                ev.append(Ev("wait_recv", me, sem=("arag", c)))
                ev.append(Ev("consume", me, key=("agchunk", c),
                             guard=("arag", c)))
                expected[me][("agchunk", c)] = 1
            for s in range(world - 1):
                ev.append(Ev("wait_send", me,
                             sem=("arag", (me - s) % world)))
        for s in range(world - 1):
            for d in cols:
                ev.append(Ev("wait_send", me, sem=("rs", d, s)))
        events[me] = ev

    from triton_dist_tpu.ops import gemm_reduce_scatter
    op = "gemm_ar" if all_gather_epilogue else "gemm_rs"
    return Trace(name=f"{op}[w{world} d{dirs}]", world=world, dirs=dirs,
                 events=events, expected=expected, outputs=outputs,
                 anchor=_anchor_of(gemm_reduce_scatter._gemm_rs_kernel))


def family_traces(world: int, dirs: int, m_tiles: int = 2,
                  n_blocks: int = 2) -> list:
    """Every fused-family schedule shape at one (world, dirs)."""
    return [
        ag_ring_trace(world, dirs),
        ag_ring_trace(world, dirs, m_tiles=m_tiles, n_blocks=n_blocks),
        gemm_rs_trace(world, dirs),
        gemm_rs_trace(world, dirs, all_gather_epilogue=True),
    ]


def verify_family(worlds=range(1, 9), dirs_list=(1, 2)) -> list:
    """Model-check every fused-family ring schedule; returns Findings."""
    from triton_dist_tpu.analysis.protocol_model import (
        violations_to_findings)
    findings = []
    for world in worlds:
        for dirs in dirs_list:
            for trace in family_traces(world, dirs):
                findings.extend(violations_to_findings(
                    trace, "ring-protocol",
                    fix_hint=("the schedule this trace mirrors "
                              "violates the ring protocol — see "
                              "docs/analysis.md 'ring-protocol'")))
    return findings


# ---------------------------------------------------------------------------
# Ring-specific mutators (the generic dropped-wait / doubled-signal
# mutators live in protocol_model and are re-exported above).
# ---------------------------------------------------------------------------

def shift_consume(trace: Trace, by: int = 1) -> Trace:
    """Off-by-one chunk-index mutant: one tile consumes the wrong
    shard (and skips the right one)."""
    t = _copy(trace)
    r, i = _first(t, "consume")
    e = t.events[r][i]
    chunk = (e.key[0] + by) % t.world
    guard = (e.guard[0], e.guard[1], chunk) if e.guard else \
        ("ag", 0, chunk)
    t.events[r][i] = dataclasses.replace(e, key=(chunk,) + e.key[1:],
                                         guard=guard)
    return t


def swap_direction(trace: Trace, rank: int = 0) -> Trace:
    """Swapped-ring-direction mutant: one rank sends every chunk the
    wrong way round — its neighbors wait on deliveries that never
    come."""
    t = _copy(trace)
    evs = t.events[rank]
    for i, e in enumerate(evs):
        if e.kind == "signal":
            sem = (e.sem[0], 1 - e.sem[1], *e.sem[2:]) \
                if len(e.sem) > 2 else e.sem
            w = t.world
            other = {(rank + 1) % w: (rank - 1) % w,
                     (rank - 1) % w: (rank + 1) % w}.get(e.dst, e.dst)
            evs[i] = dataclasses.replace(e, sem=sem, dst=other)
    return t
