"""Static model checker for the fused GEMM family's ring protocols.

The bidirectional ring schedules (ops/common.py ``ring_chunk_schedule``,
ops/allgather_gemm.py ``_make_ring``, the GEMM-RS/AR mirrored-ring
column splits in ops/gemm_reduce_scatter.py) are signal/wait protocols
whose deadlock and race bugs only manifest on chip. This module checks
them *before* any compile: it symbolically executes the schedule —
calling the kernels' own ``ring_chunk_schedule`` / ``ring_hop_counts``
with concrete (rank, step) values, then mirroring ``_make_ring``'s
copy/wait/forward structure into an explicit per-rank event trace —
and verifies, for every world size and both ``ring_dirs`` settings:

- **signal/wait balance** per (src, dst, semaphore): every remote-copy
  start is matched by exactly one ``wait_recv`` at the destination and
  one ``wait_send`` at the source (a surplus leaves a semaphore
  nonzero at kernel exit; a deficit is a hang);
- **chunk-coverage exactness**: every shard is consumed exactly once
  per output tile (and every GEMM-RS output chunk sums exactly one
  partial from every rank);
- **absence of wait-before-signal cycles**: a greedy maximal execution
  of the traces (semaphore waits are the only blocking ops and signals
  are monotonic, so the maximal execution is unique) — any rank left
  blocked is a deadlock, reported with the blocked semaphores;
- **arrival ordering** (the race the dynamic ``TDT_DETECT_RACES``
  interpreter checks at runtime): no remote chunk is read without a
  preceding wait on its delivery semaphore in program order.

The interpret-mode race detector checks only the (world, config) pairs
a CPU test happens to run; this checker enumerates worlds 1..8 x both
directions x every kernel schedule shape in milliseconds, so autotune
candidates no test ever executed are still vetted
(docs/analysis.md "ring-protocol").
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
from collections import Counter

from triton_dist_tpu.analysis.findings import Finding

__all__ = [
    "Ev", "Trace", "Violation", "ag_ring_trace", "gemm_rs_trace",
    "check_trace", "family_traces", "verify_family",
    "drop_first_wait", "double_signal", "shift_consume",
    "swap_direction",
]


@dataclasses.dataclass(frozen=True)
class Ev:
    """One protocol event in a rank's program order.

    ``signal``: a remote-copy start at ``rank`` whose recv semaphore
    ``sem`` fires at ``dst`` (and whose send semaphore fires back at
    ``rank``). ``wait_recv``/``wait_send``: blocking decrements of the
    local side of ``sem``. ``consume``: a read of output-tile ``key``
    guarded by delivery semaphore ``guard`` (``None`` = local data).
    """
    kind: str
    rank: int
    sem: tuple | None = None
    dst: int | None = None
    key: tuple | None = None
    guard: tuple | None = None


@dataclasses.dataclass
class Trace:
    """Per-rank event lists for one kernel schedule, plus the coverage
    oracle (``expected`` consume keys per rank; ``outputs`` are the
    GEMM-RS reduction results as {chunk: contributor-tuple} maps)."""
    name: str
    world: int
    dirs: int
    events: dict
    expected: dict
    outputs: list = dataclasses.field(default_factory=list)
    anchor: tuple = (None, None)


@dataclasses.dataclass(frozen=True)
class Violation:
    code: str       # ring.deadlock / ring.signal_wait_imbalance /
    #                 ring.race / ring.coverage
    detail: str


@functools.lru_cache(maxsize=None)
def _schedule_table(world: int, dirs: int):
    """{(me, s): (chunk, is_bwd, off)} from the REAL
    ``ring_chunk_schedule`` — the checker executes the kernels' own
    schedule code, not a reimplementation of it."""
    from triton_dist_tpu.ops.common import ring_chunk_schedule
    table = {}
    for me in range(world):
        for s in range(world):
            c, b, o = ring_chunk_schedule(me, s, world, dirs)
            table[(me, s)] = (int(c), bool(b), int(o))
    return table


@functools.lru_cache(maxsize=None)
def _hops(world: int, dirs: int):
    from triton_dist_tpu.ops.common import ring_hop_counts
    n_fwd, n_bwd = ring_hop_counts(world, dirs)
    return int(n_fwd), int(n_bwd)


def _anchor_of(obj) -> tuple:
    try:
        file = inspect.getsourcefile(obj)
        _, line = inspect.getsourcelines(obj)
        return file, line
    except (OSError, TypeError):
        return None, None


def ag_ring_trace(world: int, dirs: int, m_tiles: int = 1,
                  n_blocks: int = 1) -> Trace:
    """Event trace of the fused AG-GEMM family's ring schedule.

    ``m_tiles=n_blocks=1`` mirrors the vmem kernel
    (``_ag_gemm_kernel``: consume chunk s, then ``advance(s+1)``);
    tiled shapes mirror ``_ag_gemm_hbm_nb_kernel`` (ring bookkeeping at
    chunk boundaries of N-block 0 only — later N-blocks re-read the
    workspace with no waits, safe because panel 0's waits all precede
    them in program order, which the race check verifies rather than
    assumes). The AG-SwiGLU kernel shares this exact structure
    (``_ag_swiglu_hbm_kernel`` consumes each tile twice through the
    same single arrival wait, so one consume event per tile models it).
    """
    sched = _schedule_table(world, dirs)
    n_fwd, n_bwd = _hops(world, dirs)
    tiled = (m_tiles, n_blocks) != (1, 1)
    events: dict = {}
    expected: dict = {}
    for me in range(world):
        ev: list = []
        left, right = (me - 1) % world, (me + 1) % world

        def advance(s, ev=ev, me=me, left=left, right=right):
            # mirrors _make_ring.advance: position 0 launches the local
            # chunk both ways; positions 1..world-1 wait the arrival
            # and keep it travelling while hops remain; >= world no-op.
            if world == 1:
                return
            if s == 0:
                if n_fwd:
                    ev.append(Ev("signal", me, sem=("ag", 0, me),
                                 dst=right))
                if n_bwd:
                    ev.append(Ev("signal", me, sem=("ag", 1, me),
                                 dst=left))
            elif s < world:
                chunk, is_bwd, off = sched[(me, s)]
                d = 1 if is_bwd else 0
                ev.append(Ev("wait_recv", me, sem=("ag", d, chunk)))
                if off < (n_bwd if is_bwd else n_fwd):
                    ev.append(Ev("signal", me, sem=("ag", d, chunk),
                                 dst=(left if d else right)))

        def consume(spos, mt, nb, ev=ev, me=me):
            chunk, is_bwd, _ = sched[(me, spos)] if world > 1 else \
                (me, False, 0)
            guard = None if chunk == me else \
                ("ag", 1 if is_bwd else 0, chunk)
            ev.append(Ev("consume", me, key=(chunk, mt, nb),
                         guard=guard))

        if world == 1:
            for nb in range(n_blocks):
                for mt in range(m_tiles):
                    consume(0, mt, nb)
        elif not tiled:
            advance(0)
            for s in range(world):
                consume(s, 0, 0)
                advance(s + 1)
        else:
            per_nb = world * m_tiles
            total = n_blocks * per_nb

            def ring_advance(i):
                if i < per_nb and i % m_tiles == 0:
                    advance(i // m_tiles)

            ring_advance(0)
            for i in range(total):
                ring_advance(i + 1)
                consume((i % per_nb) // m_tiles, i % m_tiles,
                        i // per_nb)
        # mirrors _make_ring.drain
        if world > 1:
            for s in range(max(n_fwd, n_bwd)):
                if s < n_fwd:
                    ev.append(Ev("wait_send", me,
                                 sem=("ag", 0, (me - s) % world)))
                if n_bwd > 0 and s < n_bwd:
                    ev.append(Ev("wait_send", me,
                                 sem=("ag", 1, (me + s) % world)))
        events[me] = ev
        expected[me] = {(c, mt, nb): 1
                        for c in range(world)
                        for mt in range(m_tiles)
                        for nb in range(n_blocks)}
    from triton_dist_tpu.ops import allgather_gemm
    return Trace(name=f"ag_ring[w{world} d{dirs} "
                      f"{m_tiles}x{n_blocks}]",
                 world=world, dirs=dirs, events=events,
                 expected=expected,
                 anchor=_anchor_of(allgather_gemm._make_ring))


def _merge(a: dict, b: dict) -> dict:
    out = dict(a)
    for chunk, contribs in b.items():
        out[chunk] = out.get(chunk, ()) + contribs
    return out


def gemm_rs_trace(world: int, dirs: int,
                  all_gather_epilogue: bool = False,
                  send_idx_shift: int = 0) -> Trace:
    """Event trace of the GEMM-RS mirrored-ring schedule
    (``_gemm_rs_kernel``; the N-blocked kernel splits the same two
    rings over N-block ranges instead of column halves — identical
    protocol, so one trace shape covers both).

    ``dirs=2``: column half 0 reduces on the rightward ring (step s
    sends the partial for chunk me-s-1), half 1 on the mirrored
    leftward ring (chunk me+s+1). Reduction values are tracked
    symbolically as {chunk: contributor-tuple} maps so the checker can
    assert every output chunk sums every rank exactly once.
    ``all_gather_epilogue=True`` appends the GEMM-AR ring AG of the
    reduced chunks. ``send_idx_shift`` exists for mutation tests (an
    off-by-one chunk index feeds partials of the wrong shard into the
    travelling sum)."""
    cols = (0,) if dirs == 1 else (0, 1)
    events: dict = {}
    expected: dict = {}
    outputs: list = []

    def send_idx(r, d, s):
        idx = (r - s - 1) % world if d == 0 else (r + s + 1) % world
        return (idx + send_idx_shift) % world

    # Symbolic reduction chain: val[(r, d, s)] is the value rank r
    # sends at step s on ring d, as {chunk: contributors}.
    val: dict = {}
    for s in range(max(world - 1, 0)):
        for r in range(world):
            for d in cols:
                own = {send_idx(r, d, s): (r,)}
                if s == 0:
                    val[(r, d, s)] = own
                else:
                    src = (r - 1) % world if d == 0 else (r + 1) % world
                    val[(r, d, s)] = _merge(val[(src, d, s - 1)], own)

    for me in range(world):
        ev: list = []
        left, right = (me - 1) % world, (me + 1) % world
        if world == 1:
            for d in cols:
                outputs.append((me, d, {me: (me,)}))
                ev.append(Ev("consume", me, key=("out", me, d)))
            events[me] = ev
            expected[me] = {("out", me, d): 1 for d in cols}
            continue
        for s in range(world - 1):
            for d in cols:
                if s > 0:
                    ev.append(Ev("wait_recv", me, sem=("rs", d, s - 1)))
                ev.append(Ev("signal", me, sem=("rs", d, s),
                             dst=(right if d == 0 else left)))
        for d in cols:
            ev.append(Ev("wait_recv", me, sem=("rs", d, world - 2)))
            src = left if d == 0 else right
            outputs.append((me, d,
                            _merge(val[(src, d, world - 2)],
                                   {me: (me,)})))
            ev.append(Ev("consume", me, key=("out", me, d),
                         guard=("rs", d, world - 2)))
        expected[me] = {("out", me, d): 1 for d in cols}
        if all_gather_epilogue:
            # mirrors the ring AG epilogue: step s forwards the chunk
            # received at step s-1 (s=0: the locally reduced chunk) and
            # waits the next arrival.
            for s in range(world - 1):
                ev.append(Ev("signal", me,
                             sem=("arag", (me - s) % world), dst=right))
                c = (me - s - 1) % world
                ev.append(Ev("wait_recv", me, sem=("arag", c)))
                ev.append(Ev("consume", me, key=("agchunk", c),
                             guard=("arag", c)))
                expected[me][("agchunk", c)] = 1
            for s in range(world - 1):
                ev.append(Ev("wait_send", me,
                             sem=("arag", (me - s) % world)))
        for s in range(world - 1):
            for d in cols:
                ev.append(Ev("wait_send", me, sem=("rs", d, s)))
        events[me] = ev

    from triton_dist_tpu.ops import gemm_reduce_scatter
    op = "gemm_ar" if all_gather_epilogue else "gemm_rs"
    return Trace(name=f"{op}[w{world} d{dirs}]", world=world, dirs=dirs,
                 events=events, expected=expected, outputs=outputs,
                 anchor=_anchor_of(gemm_reduce_scatter._gemm_rs_kernel))


# ---------------------------------------------------------------------------
# Checker
# ---------------------------------------------------------------------------

def check_trace(trace: Trace) -> list:
    """All protocol violations in one trace (empty list == verified)."""
    v: list[Violation] = []
    events = trace.events

    # --- deadlock: greedy maximal execution -------------------------------
    # Waits are the only blocking ops and signals are monotonic (each
    # (dst, sem) counter only grows), so running every rank as far as
    # it can, repeatedly, reaches THE unique maximal execution: any
    # rank still blocked there is deadlocked under every schedule.
    pos = {r: 0 for r in events}
    sig_recv: Counter = Counter()   # (dst, sem) -> signals executed
    sig_send: Counter = Counter()   # (src, sem)
    got_recv: Counter = Counter()
    got_send: Counter = Counter()
    progress = True
    while progress:
        progress = False
        for r, evs in events.items():
            while pos[r] < len(evs):
                e = evs[pos[r]]
                if e.kind == "signal":
                    sig_recv[(e.dst, e.sem)] += 1
                    sig_send[(r, e.sem)] += 1
                elif e.kind == "wait_recv":
                    if got_recv[(r, e.sem)] >= sig_recv[(r, e.sem)]:
                        break
                    got_recv[(r, e.sem)] += 1
                elif e.kind == "wait_send":
                    if got_send[(r, e.sem)] >= sig_send[(r, e.sem)]:
                        break
                    got_send[(r, e.sem)] += 1
                pos[r] += 1
                progress = True
    stuck = {r: events[r][pos[r]] for r in events
             if pos[r] < len(events[r])}
    if stuck:
        blocked = ", ".join(
            f"rank {r} blocked in {e.kind} on sem {e.sem}"
            for r, e in sorted(stuck.items()))
        v.append(Violation(
            "ring.deadlock",
            f"{trace.name}: wait-before-signal cycle — {blocked}"))

    # --- signal/wait balance (full traces, independent of execution) ------
    want_recv: Counter = Counter()
    want_send: Counter = Counter()
    have_recv: Counter = Counter()
    have_send: Counter = Counter()
    for r, evs in events.items():
        for e in evs:
            if e.kind == "signal":
                have_recv[(e.dst, e.sem)] += 1
                have_send[(r, e.sem)] += 1
            elif e.kind == "wait_recv":
                want_recv[(r, e.sem)] += 1
            elif e.kind == "wait_send":
                want_send[(r, e.sem)] += 1
    for side, have, want in (("recv", have_recv, want_recv),
                             ("send", have_send, want_send)):
        for key in sorted(set(have) | set(want), key=repr):
            if have[key] != want[key]:
                rank, sem = key
                v.append(Violation(
                    "ring.signal_wait_imbalance",
                    f"{trace.name}: sem {sem} at rank {rank}: "
                    f"{have[key]} signal(s) vs {want[key]} "
                    f"wait_{side}(s)"))

    # --- arrival ordering (the static analog of TDT_DETECT_RACES) --------
    for r, evs in events.items():
        waited: set = set()
        for e in evs:
            if e.kind == "wait_recv":
                waited.add(e.sem)
            elif e.kind == "consume" and e.guard is not None \
                    and e.guard not in waited:
                v.append(Violation(
                    "ring.race",
                    f"{trace.name}: rank {r} consumes {e.key} before "
                    f"any wait on its delivery sem {e.guard} "
                    f"(read of an in-flight chunk)"))

    # --- chunk-coverage exactness -----------------------------------------
    for r, evs in events.items():
        seen = Counter(e.key for e in evs if e.kind == "consume")
        want = trace.expected.get(r, {})
        for key in sorted(set(seen) | set(want), key=repr):
            if seen[key] != want.get(key, 0):
                v.append(Violation(
                    "ring.coverage",
                    f"{trace.name}: rank {r} consumes tile {key} "
                    f"{seen[key]}x (expected {want.get(key, 0)}x)"))
    all_ranks = tuple(range(trace.world))
    for rank, unit, value in trace.outputs:
        if set(value) != {rank} or \
                tuple(sorted(value.get(rank, ()))) != all_ranks:
            v.append(Violation(
                "ring.coverage",
                f"{trace.name}: output chunk {rank} (col unit {unit}) "
                f"reduces {value!r}, want every rank's partial of "
                f"chunk {rank} exactly once"))
    return v


def family_traces(world: int, dirs: int, m_tiles: int = 2,
                  n_blocks: int = 2) -> list:
    """Every fused-family schedule shape at one (world, dirs)."""
    return [
        ag_ring_trace(world, dirs),
        ag_ring_trace(world, dirs, m_tiles=m_tiles, n_blocks=n_blocks),
        gemm_rs_trace(world, dirs),
        gemm_rs_trace(world, dirs, all_gather_epilogue=True),
    ]


def verify_family(worlds=range(1, 9), dirs_list=(1, 2)) -> list:
    """Model-check every fused-family ring schedule; returns Findings."""
    findings = []
    for world in worlds:
        for dirs in dirs_list:
            for trace in family_traces(world, dirs):
                for viol in check_trace(trace):
                    file, line = trace.anchor
                    findings.append(Finding(
                        code=viol.code, message=viol.detail,
                        file=file, line=line,
                        pass_name="ring-protocol",
                        fix_hint=("the schedule this trace mirrors "
                                  "violates the ring protocol — see "
                                  "docs/analysis.md 'ring-protocol'")))
    return findings


# ---------------------------------------------------------------------------
# Mutators (tests/test_tdt_check.py): known-bad schedule mutants. Each
# returns a NEW trace; a checker that passes all of them is untested.
# ---------------------------------------------------------------------------

def _copy(trace: Trace) -> Trace:
    return dataclasses.replace(
        trace, events={r: list(evs) for r, evs in trace.events.items()},
        expected={r: dict(x) for r, x in trace.expected.items()},
        outputs=list(trace.outputs), name=trace.name + "+mut")


def _first(trace: Trace, kind: str, rank=None) -> tuple:
    for r in sorted(trace.events):
        if rank is not None and r != rank:
            continue
        for i, e in enumerate(trace.events[r]):
            if e.kind == kind:
                return r, i
    raise ValueError(f"no {kind} event in {trace.name}")


def drop_first_wait(trace: Trace, rank=None) -> Trace:
    """Dropped-wait mutant: a chunk is read while still in flight."""
    t = _copy(trace)
    r, i = _first(t, "wait_recv", rank)
    del t.events[r][i]
    return t


def double_signal(trace: Trace, rank=None) -> Trace:
    """Doubled-signal mutant: a semaphore is left nonzero at exit."""
    t = _copy(trace)
    r, i = _first(t, "signal", rank)
    t.events[r].insert(i, t.events[r][i])
    return t


def shift_consume(trace: Trace, by: int = 1) -> Trace:
    """Off-by-one chunk-index mutant: one tile consumes the wrong
    shard (and skips the right one)."""
    t = _copy(trace)
    r, i = _first(t, "consume")
    e = t.events[r][i]
    chunk = (e.key[0] + by) % t.world
    guard = (e.guard[0], e.guard[1], chunk) if e.guard else \
        ("ag", 0, chunk)
    t.events[r][i] = dataclasses.replace(e, key=(chunk,) + e.key[1:],
                                         guard=guard)
    return t


def swap_direction(trace: Trace, rank: int = 0) -> Trace:
    """Swapped-ring-direction mutant: one rank sends every chunk the
    wrong way round — its neighbors wait on deliveries that never
    come."""
    t = _copy(trace)
    evs = t.events[rank]
    for i, e in enumerate(evs):
        if e.kind == "signal":
            sem = (e.sem[0], 1 - e.sem[1], *e.sem[2:]) \
                if len(e.sem) > 2 else e.sem
            w = t.world
            other = {(rank + 1) % w: (rank - 1) % w,
                     (rank - 1) % w: (rank + 1) % w}.get(e.dst, e.dst)
            evs[i] = dataclasses.replace(e, sem=sem, dst=other)
    return t
