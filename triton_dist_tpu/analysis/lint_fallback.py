"""Escape-hatch pass: no public op entry ships without a fallback.

Migrated from ``tools/fallback_lint.py`` (which remains as a thin
deprecation shim): every module-level function in ``ops/*.py`` with an
``impl`` parameter must either wear ``@resilient`` (and have actually
reached the router registry) or be a documented delegate of a
registered op. Findings now carry the ``file:line`` of the offending
``def`` — the shim's string list is derived from these messages, so
its output is unchanged.
"""

from __future__ import annotations

import ast
import importlib
from pathlib import Path

from triton_dist_tpu.analysis.findings import Finding

__all__ = ["DELEGATES", "EXCLUDED_MODULES", "collect_findings"]

#: Entries that intentionally carry no decorator of their own because
#: they are thin forwards into a decorated entry (the registered op
#: name on the right). The pass verifies the target op IS registered.
DELEGATES = {
    # ag_gemm(a, b) == ag_gemm_multi(a, [b]) — single-b sugar.
    "allgather_gemm.ag_gemm": "ag_gemm",
    # fp8 wire wrapper: quantize → fast_all_to_all → dequantize; the
    # custom_vjp object cannot wear the wrapper, and routing happens
    # at the inner (decorated) exchange anyway.
    "all_to_all.fast_all_to_all_fp8": "all_to_all",
}

#: Modules exempt wholesale: ``autodiff`` re-exports forward-identical
#: custom_vjp wrappers that CALL the decorated entries (double-routing
#: them would re-run the router inside its own fallback).
EXCLUDED_MODULES = {"autodiff"}


def _impl_functions(tree: ast.Module):
    """(name, lineno, has_resilient_decorator) for public module-level
    defs taking an ``impl`` parameter."""
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name.startswith("_"):
            continue
        argnames = [a.arg for a in (node.args.args
                                    + node.args.kwonlyargs)]
        if "impl" not in argnames:
            continue
        decorated = False
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = (target.attr if isinstance(target, ast.Attribute)
                    else getattr(target, "id", None))
            if name == "resilient":
                decorated = True
        yield node.name, node.lineno, decorated


def collect_findings(delegates=None) -> list:
    """Contract violations as anchored findings (empty == clean).
    ``delegates`` overrides :data:`DELEGATES` (mutation tests)."""
    import triton_dist_tpu.ops as ops_pkg
    from triton_dist_tpu.resilience import registered_fallbacks

    if delegates is None:
        delegates = DELEGATES
    ops_dir = Path(ops_pkg.__file__).parent
    findings: list = []
    candidates: list = []
    for py in sorted(ops_dir.glob("*.py")):
        if py.stem.startswith("_") or py.stem in EXCLUDED_MODULES:
            continue
        tree = ast.parse(py.read_text(), filename=str(py))
        for name, lineno, decorated in _impl_functions(tree):
            candidates.append((py, name, lineno, decorated))

    # Import the modules so the decorators have run and the router
    # registry is populated, then cross-check both directions.
    for mod in sorted({py.stem for py, _, _, _ in candidates}):
        importlib.import_module(f"triton_dist_tpu.ops.{mod}")
    registered = registered_fallbacks()
    entry_to_op = {spec.entry.rsplit("triton_dist_tpu.ops.", 1)[-1]: op
                   for op, spec in registered.items()}

    def finding(py, lineno, msg):
        findings.append(Finding(
            code="lint.fallback_uncovered", message=msg,
            file=str(py), line=lineno, pass_name="fallback-coverage",
            fix_hint="decorate the entry with @resilient (or add a "
                     "DELEGATES entry naming its registered op) — "
                     "docs/resilience.md 'Escape-hatch lint'"))

    for py, name, lineno, decorated in candidates:
        qual = f"{py.stem}.{name}"
        if decorated:
            if qual not in entry_to_op:
                finding(py, lineno,
                        f"{qual}: @resilient present in source but no "
                        f"registration reached the router (import-order "
                        f"or decorator bug?)")
            continue
        delegate_op = delegates.get(qual)
        if delegate_op is None:
            finding(py, lineno,
                    f"{qual}: public op entry with an impl= parameter "
                    f"but no @resilient decorator and no DELEGATES "
                    f"entry — every op needs an XLA escape hatch "
                    f"(docs/resilience.md)")
        elif delegate_op not in registered:
            finding(py, lineno,
                    f"{qual}: delegates to op {delegate_op!r}, which "
                    f"is not registered with the fallback router")
    return findings
