"""Tutorial 09: long-context serving — model-level SP + paged KV.

The reference's sequence-parallel story stops at layer wrappers
(SpFlashDecodeLayer, AG-attention kernels). Here the WHOLE model runs
sequence-parallel and the Engine serves it:

1. **Model-level SP** — ``DenseLLM(sp_axis=...)`` keeps activations as
   (B, S, H) with S sharded: each device holds S/w positions, so max
   context scales with the mesh. Prefill runs ring attention; decode
   runs the distributed split-KV flash decode over a sequence-sharded
   cache.
2. **Paged KV** — ``Engine(paged=True)`` swaps the contiguous cache
   for vLLM-style page pools + block tables: each serve() call admits
   its batch atomically through the native allocator (csrc/kvpool) and
   freed slots are reused by later calls.
3. **2-D tp×sp** — with a (tp, sp) grid the attention heads shard over
   tp INSIDE the sequence ring.

Everything is checked against the plain head-sharded engine: greedy
tokens must be identical.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/09_long_context_serving.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
from triton_dist_tpu.runtime.cpu_shim import maybe_reexec_with_shim

maybe_reexec_with_shim()

import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig


def _cfg():
    return ModelConfig(
        hidden_size=32, intermediate_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        vocab_size=64, max_position_embeddings=64, dtype=jnp.float32)


def serve_all(mesh_shape, axes, label, reuse=False):
    mesh = Mesh(np.array(jax.devices()).reshape(mesh_shape), axes)
    # impl="xla" keeps this tutorial quick on the CPU mesh: ALL phases
    # (incl. the paged decode, which reconstructs the contiguous view
    # via table gathers) run XLA impls. On a real TPU slice use
    # impl="pallas" — the same model-level SP/paging logic drives the
    # compiled ring + paged flash-decode kernels (tpu_smoke.py
    # sp_model/prefill_decode, tests/test_sp_model.py).
    model = DenseLLM(_cfg(), mesh=mesh, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 64,
                             jnp.int32)

    golden = Engine(model, batch=2, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar").serve(params, ids, 4)
    paged_eng = Engine(model, batch=2, max_seq=64, prefill_mode="sp",
                       decode_mode="sp", paged=True, page_size=4)
    checks = [("paged", paged_eng.serve(params, ids, 4))]
    if reuse:  # second call: freed slots are re-admitted + reused
        checks.append(("paged#2", paged_eng.serve(params, ids, 4)))
    for name, got in checks:
        assert (np.asarray(got) == np.asarray(golden)).all(), name
    print(f"{label}: model-level-SP paged serving == plain engine "
          f"(greedy, {np.asarray(golden).shape[1]} tokens/row)")
    # (the contiguous sp engine is checked against the same golden in
    # tests/test_sp_model.py — skipped here to keep the tutorial quick)


if __name__ == "__main__":
    # One 2-D grid demonstrates both capabilities at once (heads over
    # tp inside the sequence ring + paged pools). The pure-sp (1, 8)
    # shape runs the same code path — tests/test_sp_model.py covers it.
    serve_all((2, 4), ("tp", "sp"), "2-D tp2 x sp4", reuse=True)
    print("tutorial 09 complete")
