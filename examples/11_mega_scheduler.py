"""Tutorial 11: the mega task graph and its native scheduler.

Analog of the reference's MegaTritonKernel workflow
(mega_triton_kernel/models/qwen3.py + core/scheduler.py): record a whole
decoder step as a task graph, inspect the dependency structure
(wavefronts), run the HEFT critical-path scheduler (queue assignment +
speed-of-light makespan), and execute the SAME graph as one fused jit
program under both emission orders — topological and HEFT
priority-first — verifying numerics are identical. Note: emission
order does NOT change the compiled program (XLA schedules the dataflow
graph; see docs/architecture.md "Mega scheduler" for the r5
experiments demoting the scheduler to a perf-model/observability
tool). What IS live: the dependency structure fed to jit, and the
makespan perf model shown below.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/11_mega_scheduler.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_dist_tpu.mega import MegaQwen3
from triton_dist_tpu.models import DenseLLM, ModelConfig
from triton_dist_tpu.models.kv_cache import KVCacheManager


def main():
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("tp",))
    world = len(devs)
    cfg = ModelConfig(hidden_size=8 * world, intermediate_size=16 * world,
                      num_hidden_layers=3, num_attention_heads=world,
                      num_key_value_heads=world, head_dim=8,
                      vocab_size=128, max_position_embeddings=32,
                      dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    kv = KVCacheManager(cfg.num_hidden_layers, 2, 32,
                        cfg.num_key_value_heads, cfg.head_dim,
                        mesh=mesh, axis="tp", dtype=cfg.dtype)

    # 1. Record the decode step as a task graph (reference ModelBuilder).
    mega = MegaQwen3(model, decode_mode="gemm_ar")
    g = mega.graph
    n_waves, _ = g.waves()
    print(f"graph: {len(g.tasks)} tasks, {n_waves} dependency waves")

    # 2. The native scheduler (csrc/scheduler): HEFT queue assignment +
    #    makespan — a speed-of-light model of the step on n-way hardware.
    for q in (2, 4, 8):
        assign, span = g.critical_path_schedule(q)
        print(f"  {q}-queue HEFT: makespan {span} cost-units, "
              f"{len(set(assign.tolist()))} queues used")

    # 3. Execute under both emission orders; numerics must match exactly.
    mega_h = MegaQwen3(model, decode_mode="gemm_ar", order_policy="heft")
    tok = jnp.array([[11], [29]], jnp.int32)
    c_t, c_h = kv.init(), kv.init()
    for step in range(4):
        lo_t, c_t = mega.step(params, tok, c_t, step)
        lo_h, c_h = mega_h.step(params, tok, c_h, step)
        np.testing.assert_allclose(np.asarray(lo_t), np.asarray(lo_h),
                                   rtol=1e-5, atol=1e-5)
        tok = jnp.argmax(lo_t[:, -1], -1).astype(jnp.int32)[:, None]
    print("4-step decode: topo and heft emissions token-identical")

    # 4. Golden check vs the plain model forward.
    ref, _ = model.forward(params, tok, kv.init(), jnp.int32(0),
                           mode="gemm_ar")
    out, _ = mega.step(params, tok, kv.init(), 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    print("mega step == model.forward: OK")


if __name__ == "__main__":
    main()
