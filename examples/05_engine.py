"""Tutorial 05: end-to-end inference with the Engine.

Analog of the reference's e2e demo (test_e2e_inference.py / Engine.serve):
build a Qwen3-style model, prefill, then run the jit-compiled decode loop
(the CUDA-graph analog) — plus the mega one-program decode step.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/05_engine.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_dist_tpu.mega import MegaQwen3
from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
from triton_dist_tpu.models.kv_cache import KVCacheManager


def main():
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("tp",))
    world = len(devs)
    cfg = ModelConfig(hidden_size=8 * world, intermediate_size=16 * world,
                      num_hidden_layers=2, num_attention_heads=world,
                      num_key_value_heads=world, head_dim=8,
                      vocab_size=128, max_position_embeddings=32,
                      dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))

    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 4), 0,
                                cfg.vocab_size, jnp.int32)
    out = eng.serve(params, prompt, gen_len=5)
    print("generated:", np.asarray(out))

    # mega: the whole decode step as one compiled program
    mega = MegaQwen3(model, decode_mode="gemm_ar")
    kv = KVCacheManager(cfg.num_hidden_layers, 2, 32,
                        cfg.num_key_value_heads, cfg.head_dim, mesh=mesh,
                        axis="tp", dtype=cfg.dtype)
    logits, _ = mega.step(params, out[:, -1:], kv.init(), 0)
    print("mega step logits:", logits.shape)
    print(mega.graph.summary().splitlines()[0])
    print("OK")


if __name__ == "__main__":
    main()
