"""Tutorial 02: fused AllGather-GEMM and overlap measurement.

Analog of the reference's tutorials/07 (AG-GEMM) + the overlap-efficiency
methodology from BASELINE.md: run the fused collective matmul, verify
against the XLA golden, and report the measured speedup next to the
perf-model upper bound.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/02_ag_gemm_overlap.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import (
    create_ag_gemm_context, ag_gemm)
from triton_dist_tpu.runtime.utils import assert_allclose, perf_func
from triton_dist_tpu.tools import (
    estimate_all_gather_time_ms, estimate_gemm_sol_time_ms,
    overlap_efficiency)


def main():
    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("tp",))
    world = len(devs)
    m, k, n = 8 * world, 128, 32 * world

    key = jax.random.PRNGKey(0)
    a = jax.device_put(jax.random.normal(key, (m, k), jnp.float32),
                       NamedSharding(mesh, P("tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.float32),
        NamedSharding(mesh, P(None, "tp")))

    ctx = create_ag_gemm_context(mesh, "tp")
    c_fused = ag_gemm(a, b, ctx, impl="pallas")
    c_gold = ag_gemm(a, b, ctx, impl="xla")
    assert_allclose(c_fused, c_gold, rtol=1e-4, atol=1e-4)

    _, t_fused = perf_func(lambda: ag_gemm(a, b, ctx, impl="pallas"),
                           iters=5, warmup_iters=2)
    _, t_gold = perf_func(lambda: ag_gemm(a, b, ctx, impl="xla"),
                          iters=5, warmup_iters=2)
    bound = overlap_efficiency(
        estimate_gemm_sol_time_ms(m, n // world, k),
        estimate_all_gather_time_ms(m // world * k * 4, world))
    print(f"fused {t_fused:.3f} ms vs golden {t_gold:.3f} ms "
          f"(speedup {t_gold / t_fused:.2f}x, overlap bound {bound:.2f}x)")
    print("OK")


if __name__ == "__main__":
    main()
