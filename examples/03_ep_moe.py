"""Tutorial 03: expert-parallel MoE with the LL all-to-all.

Analog of the reference's tutorials/04 (DeepSeek-style inference a2a):
route tokens to expert-owning ranks, run the grouped expert FFN locally,
and combine back with routing weights.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/03_ep_moe.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers.ep_a2a import EPAll2AllLayer
from triton_dist_tpu.ops.group_gemm import grouped_expert_ffn
from triton_dist_tpu.ops.moe_utils import topk_routing


def main():
    devs = jax.devices()
    world = len(devs)
    mesh = Mesh(np.array(devs), ("ep",))
    rows, h, i, e, topk = 8, 32, 48, 2 * world, 2
    t = world * rows
    epr = e // world

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (t, h), jnp.float32)
    router = jax.random.normal(jax.random.PRNGKey(1), (h, e), jnp.float32)
    wg = jax.random.normal(jax.random.PRNGKey(2), (e, h, i), jnp.float32)
    wu = jax.random.normal(jax.random.PRNGKey(3), (e, h, i), jnp.float32)
    wd = jax.random.normal(jax.random.PRNGKey(4), (e, i, h), jnp.float32)

    weights, indices = topk_routing(x @ router, topk)

    layer = EPAll2AllLayer(max_tokens=rows, hidden=h, topk=topk,
                           num_experts=e, mesh=mesh, axis="ep",
                           dtype=jnp.float32, impl="pallas")
    sh = lambda a, s: jax.device_put(a, NamedSharding(mesh, s))

    tokens, local_expert, handle = layer.dispatch(sh(x, P("ep")),
                                                  sh(indices, P("ep")))

    def local_ffn(tok, le, g, u, d):
        return grouped_expert_ffn(tok, g, u, d, le, epr)

    out_tok = jax.shard_map(
        local_ffn, mesh=mesh, in_specs=(P("ep"),) * 5, out_specs=P("ep"),
        check_vma=False)(tokens, local_expert, sh(wg, P("ep")),
                         sh(wu, P("ep")), sh(wd, P("ep")))

    out = layer.combine(out_tok, sh(weights, P("ep")), handle)
    print("tokens routed:", int(np.asarray(handle.valid).sum()),
          "of", t * topk, "pairs; output", out.shape)
    assert bool(jnp.isfinite(out).all())

    # fp8 wire (the reference's headline LL-a2a config: tokens travel as
    # float8_e4m3fn + per-row scales — half the ICI bytes for bf16
    # models). Same layer API: wire_dtype="fp8".
    layer8 = EPAll2AllLayer(max_tokens=rows, hidden=h, topk=topk,
                            num_experts=e, mesh=mesh, axis="ep",
                            dtype=jnp.float32, impl="pallas",
                            wire_dtype="fp8")
    tok8, le8, h8 = layer8.dispatch(sh(x, P("ep")), sh(indices, P("ep")))
    out8_tok = jax.shard_map(
        local_ffn, mesh=mesh, in_specs=(P("ep"),) * 5, out_specs=P("ep"),
        check_vma=False)(tok8, le8, sh(wg, P("ep")),
                         sh(wu, P("ep")), sh(wd, P("ep")))
    out8 = layer8.combine(out8_tok, sh(weights, P("ep")), h8)
    rel = float(jnp.max(jnp.abs(out8 - out)) /
                (jnp.max(jnp.abs(out)) + 1e-9))
    print(f"fp8 wire vs full precision: rel err {rel:.4f}")
    assert rel < 0.1
    print("OK")


if __name__ == "__main__":
    main()
