"""Tutorial 07: DP as a mesh axis + GPipe microbatch pipeline.

Two capabilities beyond the reference's launcher-centric model:

1. **DP composition** — the reference replicates whole processes with
   torchrun for data parallelism (SURVEY.md §2.9 "DP: not a subsystem").
   Here DP is just another mesh axis: wrap a step in
   ``jax.shard_map(..., axis_names={"dp"})`` and every fused op nests
   inside it (``ops.common.nestable_shard_map``), its collectives staying
   within the dp slice.
2. **Pipeline scheduling** — the reference stops at p2p buffers + a test
   ("PP: partial — no scheduler"); ``layers.p2p.pipeline_schedule`` is a
   GPipe microbatch schedule as one ``lax.scan`` whose hops ride the ICI
   ring via ``ppermute``.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/07_dp_pipeline.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
from triton_dist_tpu.runtime.cpu_shim import maybe_reexec_with_shim

maybe_reexec_with_shim()

import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.layers.p2p import pipeline_schedule
from triton_dist_tpu.layers.tp_mlp import TPMLP
from triton_dist_tpu.runtime.utils import assert_allclose


def dp_composed_mlp():
    """A TP-fused MLP under an outer data-parallel axis: a (dp=2, tp=4)
    mesh where each dp slice runs the same weights on its own batch."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
    mlp = TPMLP(hidden_size=64, intermediate_size=128, mesh=mesh,
                axis="tp", dtype=jnp.float32, impl="xla")
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 64), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "tp"), None)))

    step = jax.jit(jax.shard_map(
        lambda p, v: mlp(p, v, mode="ag_rs"),
        mesh=mesh, in_specs=(P(None, None), P("dp", None)),
        out_specs=P("dp", None), axis_names={"dp"}, check_vma=False))
    out = step(params, xs)

    wg, wu, wd = (np.asarray(params[k], np.float64)
                  for k in ("w_gate", "w_up", "w_down"))
    xf = np.asarray(x, np.float64)
    ref = ((xf @ wg) / (1 + np.exp(-(xf @ wg))) * (xf @ wu)) @ wd
    assert_allclose(out, ref, rtol=5e-2, atol=5e-2)
    print("dp-composed TP-MLP: OK (dp=2 x tp=4, fused ops nested)")


def gpipe_pipeline():
    """8-stage pipeline, 4 microbatches: all stages busy in steady state;
    matches applying the stages sequentially."""
    mesh = Mesh(np.array(jax.devices()), ("pp",))
    w, rows, f, m = 8, 8, 32, 4
    ws = jax.random.normal(jax.random.PRNGKey(2), (w, f, f),
                           jnp.float32) / np.sqrt(f)
    params = {"w": jax.device_put(ws, NamedSharding(mesh, P("pp")))}
    mb = jax.random.normal(jax.random.PRNGKey(3), (m, rows, f), jnp.float32)

    def stage(p, h):
        return jnp.tanh(h @ p["w"])

    out = jax.jit(lambda p, x: pipeline_schedule(stage, p, x, mesh=mesh,
                                                 axis="pp"))(params, mb)
    ref = np.asarray(mb, np.float64)
    for s in range(w):
        ref = np.tanh(ref @ np.asarray(ws, np.float64)[s])
    assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
    print(f"gpipe pipeline: OK ({w} stages, {m} microbatches, "
          f"{m + w - 1} ticks)")


if __name__ == "__main__":
    dp_composed_mlp()
    gpipe_pipeline()
    print("tutorial 07 complete")
