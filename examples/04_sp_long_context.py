"""Tutorial 04: sequence-parallel long-context attention.

Analog of the reference's SP tutorials (AG-KV prefill + distributed
flash-decode): prefill with ring attention (KV never materialized in
full) and decode over a sequence-sharded KV cache with the cross-rank
partial-softmax combine.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/04_sp_long_context.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.flash_decode import (
    create_flash_decode_context, gqa_fwd_batch_decode)
from triton_dist_tpu.ops.sp_attention import (
    create_sp_attention_context, sp_ag_attention)


def main():
    devs = jax.devices()
    world = len(devs)
    mesh = Mesh(np.array(devs), ("sp",))
    b, s, hq, hkv, d = 1, 16 * world, 2 * world, world, 16

    key = jax.random.PRNGKey(0)
    sh = NamedSharding(mesh, P(None, "sp"))
    q = jax.device_put(jax.random.normal(key, (b, s, hq, d), jnp.float32),
                       sh)
    k = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                          jnp.float32), sh)
    v = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                          jnp.float32), sh)

    # prefill: ring attention (causal) — each device holds s/world positions
    ctx = create_sp_attention_context(mesh, "sp", causal=True)
    out = sp_ag_attention(q, k, v, ctx, impl="ring")
    print("prefill out", out.shape, "finite:",
          bool(jnp.isfinite(out).all()))

    # decode: distributed flash-decode over the same sharded KV
    dctx = create_flash_decode_context(mesh, "sp")
    qd = jax.random.normal(jax.random.PRNGKey(3), (b, hq, d), jnp.float32)
    dec = gqa_fwd_batch_decode(qd, k, v, jnp.int32(s), dctx, impl="pallas")
    print("decode out", dec.shape, "finite:", bool(jnp.isfinite(dec).all()))

    # chunked prefill: a LATER chunk of queries attends the cache-like
    # full KV with live-length masking (q_offset/kv_len) — the
    # cache-aware path behind Engine(prefill_chunk=...).
    half = s // 2
    q2 = jax.device_put(q[:, half:], sh)
    chunk_out = sp_ag_attention(q2, k, v, ctx, impl="ring",
                                q_offset=half, kv_len=s)
    np.testing.assert_allclose(np.asarray(chunk_out),
                               np.asarray(out[:, half:]), rtol=2e-4,
                               atol=2e-4)
    print("chunked prefill (second half) == single-shot second half")
    print("OK")


if __name__ == "__main__":
    main()
