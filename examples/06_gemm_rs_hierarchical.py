"""Tutorial 06: GEMM-ReduceScatter overlap + two-level (inter-node)
collectives.

Analog of the reference's tutorials/05-06 (intra/inter-node
reduce-scatter) and 08 (overlapping GEMM-ReduceScatter): run the
standalone ring reduce-scatter, the fused GEMM-RS collective matmul and
the decode-path GEMM-AR, verify each against its XLA golden, then show
the two-level ICI+DCN hierarchical collectives on a 2-D mesh — the TPU
shape of the reference's inter-node staging (reduce_scatter.py:857
``reduce_scatter_2d_op``).

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/06_gemm_rs_hierarchical.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.ops.reduce_scatter import (
    create_reduce_scatter_context, reduce_scatter)
from triton_dist_tpu.ops.gemm_reduce_scatter import (
    create_gemm_rs_context, gemm_ar, gemm_rs)
from triton_dist_tpu.ops.hierarchical import (
    create_hier_context, all_reduce_2d, reduce_scatter_2d)
from triton_dist_tpu.runtime.utils import assert_allclose


def main():
    devs = jax.devices()
    world = len(devs)
    mesh = Mesh(np.array(devs), ("tp",))

    # 1. Standalone ring reduce-scatter: (w, M, N) partials → summed
    #    row-chunks (reference tutorials/05).
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (world, world * 8, 128),
                          jnp.float32),
        NamedSharding(mesh, P("tp")))
    rs_ctx = create_reduce_scatter_context(mesh, "tp")
    got = reduce_scatter(x, rs_ctx, impl="pallas")
    assert_allclose(got, np.asarray(x, np.float64).sum(axis=0),
                    rtol=1e-5, atol=1e-5)
    print("ring reduce-scatter OK")

    # 2. Fused GEMM-RS: the row-parallel linear's collective matmul
    #    (reference tutorials/08) — the ring hop of chunk c rides under
    #    chunk c+1's MXU work inside ONE kernel.
    m, k, n = world * 8, world * 16, 128
    a = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, k), jnp.float32) / 4,
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (k, n), jnp.float32) / 4,
        NamedSharding(mesh, P("tp")))
    ctx = create_gemm_rs_context(mesh, "tp")
    fused = gemm_rs(a, b, ctx, impl="pallas")
    gold = gemm_rs(a, b, ctx, impl="xla")
    assert_allclose(fused, gold, rtol=1e-4, atol=1e-4)
    print("fused GEMM-RS OK")

    # 3. GEMM-AR: the decode path — small M, replicated output
    #    (reference gemm_allreduce.py).
    a_dec = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (world * 2, k),
                          jnp.float32) / 4,
        NamedSharding(mesh, P(None, "tp")))
    out = gemm_ar(a_dec, b, ctx, impl="pallas")
    full = (np.asarray(a_dec, np.float64) @ np.asarray(b, np.float64))
    assert_allclose(out, full, rtol=1e-3, atol=1e-3)
    print("fused GEMM-AR OK")

    # 4. Two-level collectives on a (node, chip) 2-D mesh: reduce inside
    #    the fast inner axis first, then across the slow outer axis —
    #    the reference's intra-node staging + inter-node exchange.
    mesh2 = Mesh(np.array(devs).reshape(2, world // 2), ("dcn", "ici"))
    hctx = create_hier_context(mesh2, inner="ici", outer="dcn")
    xh = jax.random.normal(jax.random.PRNGKey(4), (16, 128), jnp.float32)
    # Each device contributes the (replicated) partial; sum = world * x.
    ar = all_reduce_2d(xh, hctx)
    assert_allclose(ar, world * np.asarray(xh, np.float64),
                    rtol=1e-4, atol=1e-4)
    rs2 = reduce_scatter_2d(xh, hctx)
    assert_allclose(
        np.asarray(rs2),
        world * np.asarray(xh, np.float64)[: rs2.shape[0]],
        rtol=1e-4, atol=1e-4)
    print("two-level ICI+DCN collectives OK")
    print("OK")


if __name__ == "__main__":
    main()
