"""Tutorial 10: continuous batching — a request stream through a fixed
decode window.

Beyond the reference (its Engine serves fixed batches): serve_stream
admits the next queued prompt into a batch row the moment its occupant
finishes, so short requests never wait for the longest generation in
their batch (vLLM-style scheduling). Every row runs at its own cache
position — admission resets just that row's lane.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/10_continuous_batching.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig


def main():
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    cfg = ModelConfig(hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=8,
                      num_key_value_heads=8, head_dim=8, vocab_size=256,
                      max_position_embeddings=64, dtype=jnp.float32)
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))

    # Ten requests, two decode rows: with static batching the two
    # longest generations would gate every batch; streamed, each row
    # picks up the next prompt the moment it frees.
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, 256, size=n).tolist()
               for n in rng.integers(1, 9, size=10)]
    eng = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                 decode_mode="gemm_ar")
    results = eng.serve_stream(params, prompts, gen_len=6)

    # Greedy streamed results must equal serving each prompt alone.
    solo = Engine(model, batch=1, max_seq=32, prefill_mode="xla_ar",
                  decode_mode="gemm_ar")
    for prompt, row in zip(prompts, results):
        want = np.asarray(solo.serve(
            params, jnp.asarray([prompt], jnp.int32), 6))[0].tolist()
        assert row == want, (prompt, row, want)
    print(f"{len(prompts)} requests through a 2-row window; "
          "all token-exact vs solo serving")

    # CROSS-REQUEST continuous batching (ISSUE 5): the serving
    # scheduler shares one decode batch across concurrent clients — no
    # shared prompt list needed up front. submit() from any thread; a
    # short request admitted mid-flight retires while longer ones are
    # still decoding (docs/serving.md "Scheduler").
    from triton_dist_tpu.serving import Scheduler
    eng2 = Engine(model, batch=2, max_seq=32, prefill_mode="xla_ar",
                  decode_mode="gemm_ar")
    sched = Scheduler(eng2, params).start()
    futures = [sched.submit(p, 6) for p in prompts]
    for prompt, fut in zip(prompts, futures):
        want = np.asarray(solo.serve(
            params, jnp.asarray([prompt], jnp.int32), 6))[0].tolist()
        assert fut.result(timeout=300) == want[len(prompt):]
    sched.stop()
    print(f"{len(prompts)} concurrent submissions through the "
          "scheduler; token-exact vs solo serving")

    # The same stream through the LONG-CONTEXT engine: sequence-parallel
    # model + vLLM-style paged KV pools. Admission allocates the row's
    # pages and prefills straight into them; retirement hands the pages
    # to the next request (atomic turnover at admission).
    from jax.sharding import Mesh as _Mesh
    mesh_sp = _Mesh(np.array(jax.devices()).reshape(1, len(jax.devices())),
                    ("tp", "sp"))
    sp_model = DenseLLM(cfg, mesh=mesh_sp, axis="tp", sp_axis="sp",
                        impl="pallas", fwd_mode="sp")
    sp_params = sp_model.init(jax.random.PRNGKey(0))
    eng_paged = Engine(sp_model, batch=2, max_seq=64, prefill_mode="sp",
                       decode_mode="sp", paged=True, page_size=4)
    paged_results = eng_paged.serve_stream(sp_params, prompts[:6],
                                           gen_len=6)
    golden = Engine(sp_model, batch=1, max_seq=64, prefill_mode="xla",
                    decode_mode="xla_ar")
    for prompt, row in zip(prompts[:6], paged_results):
        want = np.asarray(golden.serve(
            sp_params, jnp.asarray([prompt], jnp.int32), 6))[0].tolist()
        assert row == want, (prompt, row, want)
    print(f"{len(paged_results)} requests streamed through 2 paged rows "
          "(page turnover); token-exact vs the plain engine")
    print("OK")


if __name__ == "__main__":
    main()
