"""Tutorial 12: model presets and the parallelism planner.

The reference's benchmark menu (Qwen3-8B/32B, Qwen3-MoE — every
published number in e2e_dense.md / mega_triton_kernel.md) as named
configs, fed through `tdt-plan`'s engine to pick a mesh, then built
via AutoLLM at a scaled-down size and run for one decode step.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/12_model_presets.py
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402

from triton_dist_tpu.models import AutoLLM, presets  # noqa: E402
from triton_dist_tpu.models.kv_cache import KVCacheManager  # noqa: E402
from triton_dist_tpu.parallel.plan import plan_parallelism  # noqa: E402
from triton_dist_tpu.runtime.dist import initialize_distributed  # noqa: E402


def main():
    # 1. The menu, with parameter counts from the shared accounting.
    print("presets:")
    for name, fn in presets.PRESETS.items():
        cfg = fn()
        kind = f"moe({cfg.num_experts}x top{cfg.num_experts_per_tok})" \
            if cfg.is_moe else "dense"
        print(f"  {name:14s} {presets.param_count(cfg) / 1e9:6.2f}B {kind}")

    # 2. Plan a mesh for each on 8 chips (v5p-class HBM).
    for name in ("qwen3-8b", "qwen3-32b", "qwen3-30b-a3b"):
        p = plan_parallelism(presets.PRESETS[name](), n_chips=8)
        mesh = {n: getattr(p, n) for n in p.axis_names}
        print(f"plan[{name} @8]: mesh={mesh} decode={p.decode_mode}"
              f" moe={p.moe_parallel}")

    # 3. Build a width/depth-scaled 30B-A3B through AutoLLM and decode
    #    one step on the 8-device mesh (full size needs a pod).
    ctx = initialize_distributed()
    cfg = dataclasses.replace(
        presets.qwen3_30b_a3b(), hidden_size=64, num_hidden_layers=2,
        num_attention_heads=8, num_key_value_heads=8, head_dim=8,
        moe_intermediate_size=32, num_experts=8, num_experts_per_tok=2,
        vocab_size=128, max_position_embeddings=32, dtype=jnp.float32)
    model = AutoLLM.build(cfg, mesh=ctx.mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    kv = KVCacheManager(cfg.num_hidden_layers, 1, 16,
                        cfg.num_key_value_heads, cfg.head_dim,
                        mesh=ctx.mesh, axis="tp", dtype=cfg.dtype)
    logits, _ = model.forward(params, jnp.ones((1, 4), jnp.int32),
                              kv.init(), 0, mode="xla_ar")
    print(f"scaled {type(model).__name__} decode ok: logits {logits.shape}")


if __name__ == "__main__":
    main()
