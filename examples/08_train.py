"""Tutorial 08: training through the fused kernels.

The reference framework is inference-only. Here the same TP model that
serves (tutorial 05) also trains, because the fused ops carry custom
VJPs built on a transpose symmetry (``ops/autodiff.py``):

    forward   AG-GEMM:  C = allgather(A) @ B
    backward  dA      = reduce_scatter(dC @ B^T)   <- that IS GEMM-RS

so a ``mode="ag_rs"`` training step overlaps compute and communication
in both directions. ``models.make_train_step`` wraps loss -> grad ->
optax update with donated buffers; DP needs no code (shard the batch
over a dp axis, XLA inserts the gradient all-reduce); ``remat=True``
trades FLOPs for activation HBM (jax.checkpoint per decoder layer).

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/08_train.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
from triton_dist_tpu.runtime.cpu_shim import maybe_reexec_with_shim

maybe_reexec_with_shim()

import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import DenseLLM, ModelConfig, make_train_step


def _cfg(world):
    return ModelConfig(
        hidden_size=16 * world, intermediate_size=32 * world,
        num_hidden_layers=2, num_attention_heads=world,
        num_key_value_heads=world, head_dim=16, vocab_size=64,
        max_position_embeddings=64, dtype=jnp.float32)


def _batch(seed=0):
    return {"input_ids": jax.random.randint(
        jax.random.PRNGKey(seed), (2, 8), 0, 64, jnp.int32)}


def train_tp():
    """Overfit one tiny batch under tp=8; the loss must fall hard."""
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    model = DenseLLM(_cfg(8), mesh=mesh, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(0))
    step, init_opt = make_train_step(model)
    opt_state, batch = init_opt(params), _batch()
    first = last = None
    for i in range(10):
        params, opt_state, m = step(params, opt_state, batch)
        first = first if first is not None else float(m["loss"])
        last = float(m["loss"])
    assert last < 0.8 * first, (first, last)
    print(f"tp=8 training: OK (loss {first:.3f} -> {last:.3f} in 10 steps)")
    return first


def train_fused(xla_first_loss):
    """mode="ag_rs": both passes ride the fused Pallas kernels; the
    step's math must equal the xla-mode step's."""
    mesh = Mesh(np.array(jax.devices()), ("tp",))
    model = DenseLLM(_cfg(8), mesh=mesh, axis="tp", impl="pallas",
                     fwd_mode="ag_rs")
    params = model.init(jax.random.PRNGKey(0))
    step, init_opt = make_train_step(model, mode="ag_rs")
    _, _, m = step(params, init_opt(params), _batch())
    fused_first = float(m["loss"])
    np.testing.assert_allclose(fused_first, xla_first_loss, rtol=2e-4)
    print(f"fused ag_rs training: OK (first-step loss {fused_first:.3f} "
          "== xla-mode, fwd+bwd through Pallas kernels)")


def train_dp_remat():
    """dp=2 x tp=4 grid with per-layer remat: batch rows sharded over
    dp, gradient all-reduce inserted by XLA from shardings alone."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("dp", "tp"))
    model = DenseLLM(_cfg(4), mesh=mesh, axis="tp", impl="xla",
                     fwd_mode="xla")
    params = model.init(jax.random.PRNGKey(1))
    step, init_opt = make_train_step(model, remat=True)
    opt_state = init_opt(params)
    batch = {"input_ids": jax.device_put(
        jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, 64, jnp.int32),
        NamedSharding(mesh, P("dp", None)))}
    losses = []
    for _ in range(5):
        params, opt_state, m = step(params, opt_state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    print(f"dp=2 x tp=4 + remat: OK (loss {losses[0]:.3f} -> "
          f"{losses[-1]:.3f})")


if __name__ == "__main__":
    first = train_tp()
    train_fused(first)
    train_dp_remat()
    print("tutorial 08 complete")
