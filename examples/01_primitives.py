"""Tutorial 01: device-side distributed primitives.

Analog of the reference's tutorials/01 (notify/wait/symm-at basics): a toy
Pallas kernel where each device pushes a value to its right neighbor with
a remote DMA and waits for the incoming one — the put+signal / wait
pattern every fused kernel builds on.

Run (no TPU needed — CPU simulation):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/01_primitives.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8-device CPU simulation by default (the axon TPU plugin overrides the
# JAX_PLATFORMS env var, so force it in-config); set TDT_EXAMPLES_ON_TPU=1
# to run on real devices instead.
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

if not os.environ.get("TDT_EXAMPLES_ON_TPU"):
    jax.config.update("jax_platforms", "cpu")

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

import triton_dist_tpu.language as dl
from triton_dist_tpu.ops.common import comm_params, resolve_interpret


def ring_pass_kernel(x_ref, o_ref, send_sem, recv_sem, *, axis, world):
    me = lax.axis_index(axis)
    right = lax.rem(me + 1, world)
    dl.barrier_all(axis)                       # peers' buffers exist
    copy = dl.remote_copy(x_ref.at[:], o_ref.at[:], right, send_sem,
                          recv_sem, axis=axis)
    copy.start()                               # put to right neighbor
    # wait for the put arriving from the LEFT neighbor (mirror descriptor)
    dl.remote_copy(x_ref.at[:], o_ref.at[:], me, send_sem, recv_sem,
                   axis=axis).wait_recv()
    copy.wait_send()


def main():
    devs = jax.devices()
    world = len(devs)
    mesh = Mesh(np.array(devs), ("x",))
    kernel = functools.partial(ring_pass_kernel, axis="x", world=world)

    def body(xs):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(xs.shape, xs.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[pltpu.SemaphoreType.DMA,
                            pltpu.SemaphoreType.DMA],
            compiler_params=comm_params(collective_id=0, world=world),
            interpret=resolve_interpret(None),
        )(xs)

    x = jnp.arange(world * 8, dtype=jnp.float32).reshape(world, 8)
    out = jax.shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P("x"),
                        check_vma=False)(x)
    print("input rows :", x[:, 0])
    print("output rows:", out[:, 0], "(each row shifted from the left)")
    assert np.allclose(np.asarray(out), np.roll(np.asarray(x), 1, axis=0))
    print("OK")


if __name__ == "__main__":
    main()
