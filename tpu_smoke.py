"""Real-TPU smoke: compile + run every Pallas op once at world=1.

VERDICT.md round-1 item 1b: every test in the suite forces interpret mode
on a CPU mesh, so Mosaic (the TPU kernel compiler) had never seen any of
the kernels. This script runs each op's ``impl="pallas"`` entry compiled
(no interpret) on the real chip with a 1-device mesh, so Mosaic
rejections surface as an actionable list instead of silently never being
exercised.

World=1 collapses the ring loops (the ``world > 1`` branches are static
Python), so this smokes the local DMA/VMEM/MXU structure of each kernel:
HBM<->VMEM async copies, double-buffered tile pipelines, scratch
semaphores, accumulation, layout constraints. The multi-chip ring
protocol itself is validated by the interpret-mode suite and the driver's
``dryrun_multichip``.

Usage: ``python tpu_smoke.py [--log tpu_smoke.log]``. Exit code 0 iff
every op compiled and ran; 1 if any op failed; 2 if the backend never
came up (same retry/partial contract as bench.py).
"""

from __future__ import annotations

import argparse
import os
import sys

import _cache_env  # noqa: F401  (persistent compile cache; pre-jax)
import time
import traceback


def _init_backend(retries: int = 3, backoff_s: float = 20.0):
    """jax.devices() with retry — the tunneled TPU backend can be
    transiently UNAVAILABLE (BENCH_r01 died on exactly this).

    ``TDT_SMOKE_CPU=1`` forces the CPU backend (harness validation while
    the tunnel is down). NOTE: must use jax.config — the JAX_PLATFORMS
    env var does NOT prevent the axon plugin from dialing the tunnel
    during plugin discovery (observed 07-31: `JAX_PLATFORMS=cpu
    jax.devices()` hangs on a wedged tunnel; config.update works)."""
    import jax
    if os.environ.get("TDT_SMOKE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    last = None
    for attempt in range(retries):
        try:
            return jax.devices()
        except Exception as e:  # noqa: BLE001 — backend init error classes vary
            last = e
            if attempt < retries - 1:
                time.sleep(backoff_s * (attempt + 1))
    raise last


def run_preflight() -> int:
    """Static-analysis preflight (docs/analysis.md): model-check the
    ring protocols and vet every autotune candidate table's VMEM
    footprint — pure Python, before the first Mosaic compile — plus
    the repo contract lints. A finding here stops the queue: two
    rounds of smoke queues were wedged by a compile hang this check
    class rejects statically (ROADMAP item 1)."""
    from triton_dist_tpu.tools.tdt_check import preflight
    print("== tdt-check preflight ==", flush=True)
    return preflight()


def run_smoke(log_path: str | None = None, only: str | None = None,
              interpret: bool = False, list_only: bool = False,
              skip: str | None = None, export_lint: bool = False,
              world: int = 1, case_timeout: float = 420.0,
              preflight: bool = True) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # The smoke exists to exercise the FUSED kernels: the resilience
    # router must never silently divert a case to its XLA fallback
    # (a smoke that "passed" on XLA would be worse than one that
    # failed; under FORCE_FUSED the router records infra failures and
    # re-raises instead of falling back). The compile watchdog below
    # still guards every case.
    os.environ.setdefault("TDT_FORCE_FUSED", "1")
    # Arm the router's OWN per-op watchdog below the case deadline so
    # a hang is recorded under the real (op, config, device_kind) key
    # the production router checks — the cross-process protection the
    # known-bad cache promises. The case-level watchdog (below) stays
    # as the backstop for hangs outside any op entry (jit, transfer).
    if not list_only:
        os.environ.setdefault("TDT_COMPILE_TIMEOUT_S",
                              str(max(case_timeout * 0.8, 1.0)))

    # Every smoke run records the event timeline and leaves a merged,
    # validated trace artifact next to the log (docs/observability.md
    # "Tracing") — a smoke hang then comes with its flight record for
    # free (the router auto-dumps on the watchdog trip).
    from triton_dist_tpu import obs as _obs
    from triton_dist_tpu.obs import trace as _trace
    if not list_only:
        _obs.enable()
        _trace.enable()

    if preflight and not list_only:
        rc = run_preflight()
        if rc != 0:
            print("tdt-check preflight FAILED — queue not started "
                  "(--no-preflight overrides)", flush=True)
            return rc

    results: list[tuple[str, str, str]] = []  # (name, status, detail)

    from triton_dist_tpu.runtime.utils import tree_all_finite as _finite

    skips = [s for s in (skip or "").split(",") if s]

    def case(name, fn):
        if list_only:
            print(name)
            return
        if any(s == name for s in skips):
            return
        if only:
            # "=name" selects exactly; otherwise substring filter.
            if only.startswith("="):
                if name != only[1:]:
                    return
            elif only not in name:
                return
        from triton_dist_tpu.resilience import (CompileTimeout,
                                                known_bad_cache,
                                                run_with_timeout)
        t0 = time.perf_counter()

        def run_case():
            if export_lint:
                # Lower + serialize the case for the TPU platform on
                # this (CPU) host: runs the Pallas→Mosaic lowering and
                # its VERIFIER, which rejects e.g. multi-batch-dim
                # tpu.matmul — the exact class the interpret-mode suite
                # cannot see (VERDICT r2 weak 2: "127 CPU tests pass
                # because the interpreter doesn't enforce MXU
                # constraints"). No kernel executes.
                from jax import export as jexport
                jexport.export(jax.jit(fn), platforms=("tpu",))()
                return None, True
            out = fn()
            jax.block_until_ready(out)
            return out, _finite(out)

        try:
            # Every case runs under the compile watchdog: a Mosaic
            # hang marks THIS case TIMEOUT and the queue advances —
            # the r5 failure mode was one hang wedging every case
            # behind it. The worker thread is abandoned, never killed
            # (killing mid-compile is the known tunnel-wedge trigger).
            # The span's un-ended begin event is what a flight record
            # of a hung case shows as "in flight".
            with _trace.span(f"smoke.{name}", "op"):
                out, ok = run_with_timeout(run_case, case_timeout,
                                           op=f"smoke:{name}")
            dt = time.perf_counter() - t0
            results.append((name, "PASS" if ok else "NONFINITE",
                            f"{dt:.1f}s"))
        except CompileTimeout as e:
            dt = time.perf_counter() - t0
            known_bad_cache().record(f"smoke:{name}", "case",
                                    dev.device_kind
                                    if hasattr(dev, "device_kind")
                                    else dev.platform,
                                    reason=str(e))
            # e.timeout_s distinguishes the router's inner per-op trip
            # (0.8x, real op key recorded) from the case-level backstop.
            results.append((name, "TIMEOUT",
                            f"{dt:.1f}s abandoned after "
                            f"{e.timeout_s:.0f}s (known-bad recorded; "
                            f"queue advances)"))
        except Exception as e:  # noqa: BLE001 — record and continue
            dt = time.perf_counter() - t0
            tb = traceback.format_exc().strip().splitlines()
            # The exception repr, not tb[-1]: JAX appends its
            # traceback-filter notice as the last line, which is what
            # the round-5 SP failure summary consisted of entirely.
            head = f"{type(e).__name__}: {e}".replace("\n", " ")
            results.append((name, "FAIL", f"{dt:.1f}s " + head[:160]))
            if log_path:
                with open(log_path, "a") as f:
                    f.write(f"\n=== {name} ===\n")
                    f.write("\n".join(tb) + "\n")
        print(f"  {results[-1][0]:<28} {results[-1][1]:<9} "
              f"{results[-1][2]}", flush=True)

    # Device-profile capture for the fused-family cases (ISSUE 10,
    # docs/perf.md "Overlap accounting" measured tier): each wrapped
    # case runs under jax.profiler and the capture is parsed back via
    # obs.devprof — the end-of-run PROFILE lines carry measured
    # compute/comm attribution per op, and an unparseable capture
    # fails the run (same contract as the TRACE artifact).
    prof_results: dict[str, dict] = {}

    def profiled(op, fn):
        if list_only or export_lint:
            return fn

        def wrapped():
            from triton_dist_tpu.obs import devprof
            from triton_dist_tpu.tools.profiler import group_profile
            try:
                cm = group_profile(f"smoke_{op.replace('/', '_')}",
                                   devprof.devprof_dir())
                cap = cm.__enter__()
            except Exception as e:  # noqa: BLE001 — still smoke the op
                prof_results[op] = {
                    "error": f"capture failed: {type(e).__name__}: {e}"}
                return fn()
            try:
                out = fn()
                jax.block_until_ready(out)
            finally:
                cm.__exit__(None, None, None)
            try:
                summary = devprof.parse_capture(cap.path)
                devprof.publish(summary)
                prof_results[op] = {"path": cap.path,
                                    "summary": summary}
            except Exception as e:  # noqa: BLE001 — reported, fails the run
                prof_results[op] = {
                    "path": cap.path,
                    "error": f"{type(e).__name__}: {e}"}
            return out
        return wrapped

    if list_only or export_lint:
        # Name-collection and export-lint run on CPU (work even while
        # the TPU tunnel is wedged); export-lint lowers each case FOR
        # the tpu platform without executing it.
        jax.config.update("jax_platforms", "cpu")
        if export_lint:
            os.environ["TDT_FORCE_COMPILED"] = "1"
        devices = jax.devices()
    else:
        try:
            devices = _init_backend()
        except Exception:  # noqa: BLE001
            traceback.print_exc()
            print("SMOKE: backend unavailable")
            return 2
    dev = devices[0]
    if not list_only:
        mode = "EXPORT-LINT (tpu lowering on cpu host)" if export_lint \
            else f"{dev.platform}:{getattr(dev, 'device_kind', '?')}"
        print(f"SMOKE on {mode}", flush=True)
    assert world == 1 or export_lint, (
        "world > 1 is an export-lint mode (the chip is a single device; "
        "multi-device execution is the interpret suite's job)")
    assert len(devices) >= world, (len(devices), world)
    mesh = Mesh(np.array(devices[:world]), ("tp",))
    key = jax.random.PRNGKey(0)
    bf16 = jnp.bfloat16

    def sharded(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    def randn(shape, dtype=bf16, k=0):
        return jax.random.normal(jax.random.PRNGKey(k), shape, jnp.float32
                                 ).astype(dtype)

    # --- collectives ------------------------------------------------------
    from triton_dist_tpu.ops.allgather import (
        AllGatherMethod, create_allgather_context, all_gather)
    x = sharded(randn((256, 256)), P("tp"))
    for method in (AllGatherMethod.RING_1D, AllGatherMethod.RING_BIDIR,
                   AllGatherMethod.FULL_MESH_PUSH):
        ctx = create_allgather_context(mesh, "tp", method=method,
                                       interpret=interpret)
        case(f"allgather/{method.name.lower()}",
             lambda ctx=ctx: all_gather(x, ctx, impl="pallas"))

    # Latency-class payload: one (16,128) bf16 tile per rank (reference
    # test_ag_small_msg.py / LL-allgather regime).
    xsm = sharded(randn((16, 128)), P("tp"))
    sm_ctx = create_allgather_context(
        mesh, "tp", method=AllGatherMethod.FULL_MESH_PUSH,
        interpret=interpret)
    case("allgather/small_msg",
         lambda: all_gather(xsm, sm_ctx, impl="pallas"))

    from triton_dist_tpu.ops.reduce_scatter import (
        ReduceScatterMethod, create_reduce_scatter_context, reduce_scatter)
    xp = sharded(randn((world, 256, 256)), P("tp"))  # (w, M, N) partials
    for method in (ReduceScatterMethod.RING, ReduceScatterMethod.ONE_SHOT):
        ctx = create_reduce_scatter_context(mesh, "tp", interpret=interpret)
        ctx.method = method
        case(f"reduce_scatter/{method.value}",
             lambda ctx=ctx: reduce_scatter(xp, ctx, impl="pallas"))

    from triton_dist_tpu.ops.allreduce import (
        AllReduceMethod, create_allreduce_context, all_reduce)
    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
                   AllReduceMethod.RECURSIVE_DOUBLING):
        ctx = create_allreduce_context(mesh, "tp", interpret=interpret)
        ctx.method = method
        case(f"allreduce/{method.value}",
             lambda ctx=ctx: all_reduce(xp, ctx, impl="pallas"))

    # --- fused GEMM ops ---------------------------------------------------
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm, ag_gemm_multi)
    a = sharded(randn((512, 512)), P("tp"))
    b = sharded(randn((512, 512), k=1), P(None, "tp"))
    for variant in ("vmem", "hbm"):
        ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
        ctx.variant = variant
        case(f"ag_gemm/{variant}",
             lambda ctx=ctx: ag_gemm(a, b, ctx, impl="pallas"))
    ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
    b2 = sharded(randn((512, 256), k=2), P(None, "tp"))
    case("ag_gemm_multi",
         lambda: ag_gemm_multi(a, [b, b2], ctx, impl="pallas"))

    # Bench-shape hbm cases (VERDICT r2: smoke at 512^2 missed the
    # 16.5 MB VMEM crash that killed BENCH_r02 at 2048x4096x4096).
    ab = sharded(randn((2048, 4096)), P("tp"))
    bb = sharded(randn((4096, 4096), k=13), P(None, "tp"))
    bench_ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
    case("ag_gemm/bench_shape",
         profiled("ag_gemm",
                  lambda: ag_gemm(ab, bb, bench_ctx, impl="pallas")))
    inj_ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
    inj_ctx.for_correctness = True
    inj_ctx.straggler_option = (0, 10000)
    case("ag_gemm/injection",
         lambda: ag_gemm(a, b, inj_ctx, impl="pallas"))

    # Fused AG + dual-GEMM + SwiGLU (the MLP front half as one kernel).
    from triton_dist_tpu.ops.allgather_gemm import ag_swiglu
    sw_ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
    case("ag_swiglu/small",
         lambda: ag_swiglu(a, b, b, sw_ctx, impl="pallas"))
    bu = sharded(randn((4096, 4096), k=17), P(None, "tp"))
    sw_bench_ctx = create_ag_gemm_context(mesh, "tp", interpret=interpret)
    case("ag_swiglu/bench_shape",
         profiled("ag_swiglu",
                  lambda: ag_swiglu(ab, bb, bu, sw_bench_ctx,
                                    impl="pallas")))

    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs, gemm_ar)
    rs_ctx2 = create_gemm_rs_context(mesh, "tp", interpret=interpret)
    a_rs = sharded(randn((512, 512)), P(None, "tp"))
    b_rs = sharded(randn((512, 512), k=3), P("tp"))
    case("gemm_rs", lambda: gemm_rs(a_rs, b_rs, rs_ctx2, impl="pallas"))
    case("gemm_ar", lambda: gemm_ar(a_rs, b_rs, rs_ctx2, impl="pallas"))
    a_rsb = sharded(randn((2048, 4096)), P(None, "tp"))
    b_rsb = sharded(randn((4096, 4096), k=14), P("tp"))
    case("gemm_rs/bench_shape",
         profiled("gemm_rs",
                  lambda: gemm_rs(a_rsb, b_rsb, rs_ctx2,
                                  impl="pallas")))
    # Decode GEMM-AR at production width via the hbm epilogue path
    # (VERDICT r2 next 5).
    a_ar = sharded(randn((128, 4096)), P(None, "tp"))
    case("gemm_ar/decode_shape",
         profiled("gemm_ar",
                  lambda: gemm_ar(a_ar, b_rsb, rs_ctx2,
                                  impl="pallas")))

    # --- EP / MoE ---------------------------------------------------------
    from triton_dist_tpu.ops.all_to_all import (
        create_all_to_all_context, fast_all_to_all)
    a2a_ctx = create_all_to_all_context(mesh, "tp", interpret=interpret)
    send = sharded(randn((world * world, 128, 256)), P("tp"))
    counts = sharded(jnp.full((world * world,), 64, jnp.int32), P("tp"))
    case("fast_all_to_all",
         lambda: fast_all_to_all(send, counts, a2a_ctx, impl="pallas")[0])

    from triton_dist_tpu.ops.group_gemm import (
        create_ag_group_gemm_context, ag_group_gemm)
    gg_ctx = create_ag_group_gemm_context(mesh, "tp")
    xg = sharded(randn((128, 256)), P("tp"))
    wg = sharded(randn((4, 256, 512), k=4), P(None, None, "tp"))
    eid = sharded(jax.random.randint(key, (128,), 0, 4, jnp.int32), P("tp"))
    case("ag_group_gemm",
         lambda: ag_group_gemm(xg, wg, eid, 4, gg_ctx, impl="ring"))
    case("ag_group_gemm/fused",
         lambda: ag_group_gemm(xg, wg, eid, 4, gg_ctx, impl="fused"))

    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    t_tok, topk, n_exp, inter, hid = 64, 2, 4, 512, 256
    mrs_ctx = create_moe_rs_context(mesh, "tp", num_experts=n_exp,
                                    topk=topk)
    act = sharded(randn((t_tok * topk, inter)), P(None, "tp"))
    wdown = sharded(randn((n_exp, inter, hid), k=5), P(None, "tp"))
    eid2 = jax.random.randint(key, (t_tok * topk,), 0, n_exp, jnp.int32)
    wts = jax.nn.softmax(randn((t_tok, topk), jnp.float32, k=6))
    case("moe_reduce_rs",
         lambda: moe_reduce_rs(act, wdown, eid2, wts, mrs_ctx,
                               impl="ring"))
    case("moe_reduce_rs/fused",
         lambda: moe_reduce_rs(act, wdown, eid2, wts, mrs_ctx,
                               impl="fused"))

    # --- SP attention -----------------------------------------------------
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    fd_ctx = create_flash_decode_context(mesh, "tp", interpret=interpret)
    bq, hq, hkv, hd, t = 2, 8, 2, 128, 1024
    q = randn((bq, hq, hd))
    kc = sharded(randn((bq, t, hkv, hd), k=7), P(None, "tp"))
    vc = sharded(randn((bq, t, hkv, hd), k=8), P(None, "tp"))
    case("flash_decode",
         lambda: gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t // 2), fd_ctx,
                                      impl="pallas"))

    from triton_dist_tpu.ops.flash_decode import gqa_fwd_batch_decode_paged
    fd_tiled = create_flash_decode_context(mesh, "tp", variant="tiled",
                                           t_blk=256, interpret=interpret)
    case("flash_decode/tiled",
         lambda: gqa_fwd_batch_decode(q, kc, vc, jnp.int32(t // 2),
                                      fd_tiled, impl="pallas"))
    n_pages, page = 4, 256
    # n_pages is PER-DEVICE; pools/tables are per-device slabs sharded
    # on the leading dim (world-parametric for --export-lint --world N).
    pool_k = sharded(randn((world * (bq * n_pages + 2), page, hkv, hd),
                           k=11), P("tp"))
    pool_v = sharded(randn((world * (bq * n_pages + 2), page, hkv, hd),
                           k=12), P("tp"))
    table = sharded(
        jnp.tile(jnp.arange(bq * n_pages, dtype=jnp.int32
                            ).reshape(1, bq, n_pages), (world, 1, 1)),
        P("tp"))
    # The production paged route: table-gather view + the proven dense
    # tiled kernel (paged_variant="gathered", the context default).
    # The former "flash_decode/paged" case — the DIRECT block-table
    # kernel pinned as the compile watchdog's live canary — is RETIRED
    # after wedging two rounds of smoke queues without producing a
    # root cause; docs/resilience.md "Retired canary" has the full
    # rationale. The direct kernel itself remains available as the
    # TDT_PAGED_VARIANT="direct" opt-in, guarded by the known-bad
    # cache like every other config.
    fd_paged_g = create_flash_decode_context(mesh, "tp",
                                             interpret=interpret)
    case("flash_decode/paged_gathered",
         lambda: gqa_fwd_batch_decode_paged(
             q, pool_k, pool_v, table,
             jnp.int32(world * n_pages * page // 2), fd_paged_g))

    # Serving shape (bench.py flash_decode line: B=8, 32 heads, t=8k).
    def fd_serving():
        bs, hqs, hkvs, ds, ts = 8, 32, 8, 128, 8192
        qv = randn((bs, hqs, ds), k=15)
        kcs = sharded(randn((bs, ts, hkvs, ds), k=16), P(None, "tp"))
        vcs = sharded(randn((bs, ts, hkvs, ds), k=17), P(None, "tp"))
        ctx = create_flash_decode_context(mesh, "tp", variant="tiled",
                                          t_blk=512, interpret=interpret)
        return gqa_fwd_batch_decode(qv, kcs, vcs, jnp.int32(ts - 7), ctx,
                                    impl="pallas")
    case("flash_decode/serving_shape", fd_serving)

    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    sp_ctx = create_sp_attention_context(mesh, "tp", causal=True,
                                         interpret=interpret)
    s = 512
    hkv_sp = max(2, world)          # ulysses needs heads % world == 0
    qs = sharded(randn((2, s, 4 * hkv_sp, 128)), P(None, "tp"))
    ks = sharded(randn((2, s, hkv_sp, 128), k=9), P(None, "tp"))
    vs = sharded(randn((2, s, hkv_sp, 128), k=10), P(None, "tp"))
    for impl in ("ring", "pallas"):
        case(f"sp_ag_attention/{impl}",
             lambda impl=impl: sp_ag_attention(qs, ks, vs, sp_ctx,
                                               impl=impl))
    case("sp_ag_attention/ulysses",
         lambda: sp_ag_attention(qs, ks, vs, sp_ctx, impl="ulysses"))

    # EP-mode MoE layer end-to-end, world=1-compilable (VERDICT r2
    # next 6; reference test_ep_moe_inference.py).
    def ep_moe_case():
        from triton_dist_tpu.layers.ep_moe import EPMoE
        layer = EPMoE(256, 512, num_experts=max(4, 2 * world),
                      topk=2, mesh=mesh,
                      axis="tp", dtype=bf16)
        params = layer.init(jax.random.PRNGKey(3))
        xe = sharded(randn((64, 256), k=18), P("tp"))
        return layer(params, xe)
    case("ep_moe", ep_moe_case)

    # --- PP ---------------------------------------------------------------
    from triton_dist_tpu.ops.p2p import create_p2p_context, pp_shift
    pp_ctx = create_p2p_context(mesh, "tp", interpret=interpret)
    xpp = sharded(randn((world, 128, 256)), P("tp"))
    case("pp_shift", lambda: pp_shift(xpp, pp_ctx, impl="pallas"))

    # --- layers / models --------------------------------------------------
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    mlp = TPMLP(512, 1024, mesh=mesh, axis="tp", dtype=bf16)
    mlp_p = mlp.init(key)
    xm = sharded(randn((256, 512)), P("tp"))
    for mode in ("ag_rs", "gemm_ar"):
        case(f"tp_mlp/{mode}", lambda mode=mode: mlp(mlp_p, xm, mode=mode))

    def dense_step():
        import __graft_entry__ as ge
        fn, args = ge.entry()
        out, _ = jax.jit(fn)(*args)
        return out
    case("dense_llm_step", dense_step)

    def mega_step():
        from triton_dist_tpu.mega import MegaQwen3
        from triton_dist_tpu.models import DenseLLM, ModelConfig
        from triton_dist_tpu.models.kv_cache import KVCacheManager
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2,
                          num_attention_heads=max(4, world),
                          num_key_value_heads=max(2, world), head_dim=64,
                          vocab_size=128, max_position_embeddings=32,
                          dtype=bf16)
        model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas")
        params = model.init(key)
        kv = KVCacheManager(cfg.num_hidden_layers, 2, 16,
                            cfg.num_key_value_heads, cfg.head_dim,
                            mesh=mesh, axis="tp", dtype=cfg.dtype)
        mega = MegaQwen3(model, decode_mode="gemm_ar")
        token = jnp.array([[5], [7]], jnp.int32)
        out, _ = mega.step(params, token, kv.init(), 0)
        return out
    case("mega_qwen3", mega_step)

    # Fused kernel nested under an outer DP axis (compiled-mode path the
    # CPU suite cannot cover — tests/test_dp_compose.py docstring).
    def dp_nested():
        # Use a real 2-slice dp axis when the host has >1 device; the
        # 1-chip bench host degenerates to 1x1 (structure-only check).
        nd = len(devices) if len(devices) % 2 == 0 else 1
        shape = (2, nd // 2) if nd >= 2 else (1, 1)
        mesh2 = Mesh(np.array(devices[:max(nd, 1)]).reshape(shape),
                     ("dp", "tp"))
        ctx = create_ag_gemm_context(mesh2, "tp", interpret=interpret)
        ad = jax.device_put(randn((256, 256)),
                            NamedSharding(mesh2, P(("dp", "tp"), None)))
        bd = jax.device_put(randn((256, 256), k=19),
                            NamedSharding(mesh2, P(None, "tp")))
        f = jax.jit(jax.shard_map(
            lambda a, b: ag_gemm(a, b, ctx, impl="pallas"),
            mesh=mesh2, in_specs=(P("dp", None), P(None, None)),
            out_specs=P("dp", None), axis_names={"dp"}, check_vma=False))
        return f(ad, bd)
    case("dp_compose/nested", dp_nested)

    def sp_model_step():
        # Model-level SP (round 3): forward_sp prefill + one flash-
        # decode step over the seq-sharded cache. world=1 on the bench
        # chip; the pallas flash-decode path still compiles.
        from triton_dist_tpu.models import DenseLLM, ModelConfig
        from triton_dist_tpu.models.kv_cache import KVCacheManager
        # (1, world) tp x sp grid: at --export-lint --world N this
        # lints the seq-sharded model path's multi-device lowering
        # (review r3h finding 1: it was pinned to 1 device).
        mesh2 = Mesh(np.array(devices[:world]).reshape(1, world),
                     ("tp", "sp"))
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2,
                          num_attention_heads=max(8, world),
                          num_key_value_heads=max(4, world), head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=bf16)
        model = DenseLLM(cfg, mesh=mesh2, axis="tp", sp_axis="sp",
                         impl="pallas", fwd_mode="sp")
        params = model.init(jax.random.PRNGKey(30))
        kv = KVCacheManager(cfg.num_hidden_layers, 2,
                            cfg.max_position_embeddings,
                            cfg.num_key_value_heads, cfg.head_dim,
                            mesh=mesh2, axis="sp", seq_shard=True,
                            dtype=bf16)
        ids = jax.random.randint(jax.random.PRNGKey(31), (2, 256), 0,
                                 2048, jnp.int32)
        lo, caches = jax.jit(
            lambda p, i, c: model.forward(p, i, c, 0, mode="sp"))(
            params, ids, kv.init())
        dec, _ = jax.jit(
            lambda p, i, c: model.forward(p, i, c, 256, mode="sp"))(
            params, ids[:, :1], caches)
        return lo, dec
    case("sp_model/prefill_decode", sp_model_step)

    def moe_sp_step():
        # Model-level SP MoE (round 3 session 5): seq-sharded forward
        # with the row-local MoE FFN; world=1 on the bench chip — the
        # kernels inside (ring attn, flash decode, ragged_dot) are
        # individually smoked above, this compiles the composition.
        from triton_dist_tpu.models import ModelConfig, Qwen3MoE
        from triton_dist_tpu.models.kv_cache import KVCacheManager
        mesh3 = Mesh(np.array(devices[:1]).reshape(1, 1), ("tp", "sp"))
        cfgm = ModelConfig(hidden_size=512, intermediate_size=0,
                           moe_intermediate_size=512,
                           num_hidden_layers=2, num_attention_heads=8,
                           num_key_value_heads=4, head_dim=64,
                           vocab_size=2048, max_position_embeddings=512,
                           dtype=bf16, num_experts=8,
                           num_experts_per_tok=2)
        mm = Qwen3MoE(cfgm, mesh=mesh3, axis="tp", sp_axis="sp",
                      impl="pallas", fwd_mode="sp")
        pm = mm.init(jax.random.PRNGKey(40))
        kvm = KVCacheManager(cfgm.num_hidden_layers, 2, 512,
                             cfgm.num_key_value_heads, cfgm.head_dim,
                             mesh=mesh3, axis="sp", seq_shard=True,
                             dtype=bf16)
        idsm = jax.random.randint(jax.random.PRNGKey(41), (2, 256), 0,
                                  2048, jnp.int32)
        lo, cachesm = jax.jit(
            lambda p, i, c: mm.forward(p, i, c, 0, mode="sp"))(
            pm, idsm, kvm.init())
        dec, _ = jax.jit(
            lambda p, i, c: mm.forward(p, i, c, 256, mode="sp"))(
            pm, idsm[:, :1], cachesm)
        return lo, dec
    case("moe_sp_model/prefill_decode", moe_sp_step)

    # fp8-wire a2a last among non-risky cases: first-ever int8-payload
    # DMA compile (reference's headline LL-a2a fp8 config).
    def a2a_fp8_case():
        from triton_dist_tpu.ops.all_to_all import fast_all_to_all_fp8
        send8 = sharded(randn((world * world, 128, 256)), P("tp"))
        counts8 = sharded(jnp.full((world * world,), 64, jnp.int32), P("tp"))
        return fast_all_to_all_fp8(send8, counts8, a2a_ctx,
                                   impl="pallas")[0]
    case("fast_all_to_all/fp8", a2a_fp8_case)

    def train_step():
        # Fused-mode training step (round 3): compiles the TRANSPOSE
        # fused kernels in the backward (ops/autodiff.py) on the chip —
        # forward AG-GEMM/GEMM-RS plus their GEMM-RS/AG-GEMM adjoints.
        from triton_dist_tpu.models import (DenseLLM, ModelConfig,
                                            make_train_step)
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2,
                          num_attention_heads=max(8, world),
                          num_key_value_heads=max(4, world), head_dim=64,
                          vocab_size=2048, max_position_embeddings=256,
                          dtype=bf16)
        model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas",
                         fwd_mode="ag_rs")
        params = model.init(jax.random.PRNGKey(32))
        step, init_opt = make_train_step(model, mode="ag_rs")
        batch = {"input_ids": jax.random.randint(
            jax.random.PRNGKey(33), (2, 128), 0, 2048, jnp.int32)}
        _, _, metrics = step(params, init_opt(params), batch)
        return metrics
    case("train/fused_step", train_step)

    # --- report -----------------------------------------------------------
    if list_only:
        return 0
    n_fail = sum(1 for _, st, _ in results if st != "PASS")
    width = max(len(n) for n, _, _ in results) if results else 1
    lines = [f"{n:<{width}}  {st:<9} {d}" for n, st, d in results]
    # The merged trace artifact: every host's events gathered rank-0
    # style, written next to the log, schema-validated — so each smoke
    # run ends with a Perfetto-loadable timeline of what it did.
    # Single-exact-case runs (--subproc children all share one --log)
    # get the case name in the path so per-case artifacts don't
    # clobber each other.
    try:
        from triton_dist_tpu.tools import trace_export as _texp
        suffix = ""
        if only and only.startswith("="):
            suffix = "." + only[1:].replace("/", "_")
        trace_path = ((log_path or "tpu_smoke.log") + suffix
                      + ".trace.json")
        chrome = _texp.gather_to_chrome(process_name="tpu_smoke")
        _texp.write_trace(chrome, trace_path)
        errors, warns = _texp.validate(chrome)
        lines.append(
            f"TRACE {trace_path} "
            f"({len(chrome['traceEvents'])} events, "
            f"{len(warns)} in-flight) "
            + ("valid" if not errors
               else f"INVALID: {'; '.join(errors[:3])}"))
        if errors:
            n_fail += 1
    except Exception as e:  # noqa: BLE001 — the artifact must not fail the run
        lines.append(f"TRACE export failed: {type(e).__name__}: {e}")
    # Measured device-time attribution per fused-family op (parsed
    # back from the per-case jax.profiler captures). An unparseable
    # capture IS a failure: the next chip window's overlap numbers
    # must be machine-recorded, not eyeballed (ROADMAP item 5).
    for op in sorted(prof_results):
        rec = prof_results[op]
        if "error" in rec or "summary" not in rec:
            lines.append(f"PROFILE {op} INVALID "
                         f"{rec.get('error', 'no summary')} "
                         f"({rec.get('path', '-')})")
            n_fail += 1
            continue
        m = rec["summary"].get("ops", {}).get(op)
        if m is None:
            lines.append(
                f"PROFILE {op} UNATTRIBUTED (no device.{op} label in "
                f"window — see tdt-check annotation-coverage) "
                f"({rec['path']})")
            n_fail += 1
            continue
        ov = (f"overlap_measured {m['overlap_pct']}%"
              if m["overlap_pct"] is not None
              else "overlap_requires_chip (no comm in window)")
        lines.append(f"PROFILE {op} compute {m['compute_ms']} ms "
                     f"comm {m['comm_ms']} ms {ov} ({rec['path']})")
    lines.append(f"TOTAL {len(results)} ops, {n_fail} failing")
    report = "\n".join(lines)
    print(report)
    if log_path:
        with open(log_path, "a") as f:
            f.write(report + "\n")
    return 1 if n_fail else 0


def run_subproc(log_path: str, timeout_s: float,
                skip: str | None = None,
                start_after: str | None = None,
                only: str | None = None,
                preflight: bool = True) -> int:
    """Run every case in its OWN subprocess with a hard deadline.

    A Mosaic compile hang through the tunnel has been observed to wedge
    the backend for hours (round 3); per-case isolation bounds the blast
    radius. Hung cases are ABANDONED, never killed: SIGKILLing a client
    mid-compile is the known tunnel-wedge trigger (BENCH_NOTES_r3.md,
    wedges #2/#3/#4).

    Children run with ``--hard-exit`` (os._exit after flushing results),
    skipping JAX backend teardown: a teardown that waits on the tunnel
    has been observed to linger for minutes and once wedged the whole
    run (03:23 on 07-31 — the case PASSed, the process never exited).
    The case's own output is authoritative: a lingering child whose
    output already says PASS/FAIL is scored as such and the run
    CONTINUES; a case with no written result is a genuine compile hang,
    scored TIMEOUT and recorded in the resilience known-bad cache — the
    QUEUE ADVANCES past it (the r5 whole-queue wedge class: one bad
    kernel must not cost the rest of the round). TWO CONSECUTIVE hangs
    mean the tunnel itself is wedged, not a kernel: every later case
    would queue behind the same stuck compile and burn a full timeout
    each (and a second known-bad record would blame a case that never
    got to compile), so the run stops there. ``--start-after`` resumes
    a partial run."""
    import subprocess
    # Preflight ONCE in the parent (children get --no-preflight): a
    # protocol or VMEM-budget finding stops the queue before the first
    # child ever dials the tunnel (docs/analysis.md).
    if preflight:
        rc = run_preflight()
        if rc != 0:
            print("tdt-check preflight FAILED — queue not started "
                  "(--no-preflight overrides)", flush=True)
            return rc
    names = subprocess.run(
        [sys.executable, __file__, "--list"], capture_output=True,
        text=True, timeout=600).stdout.split()
    skips = [s for s in (skip or "").split(",") if s]
    names = [n for n in names if n not in skips]
    if only:
        names = [n for n in names
                 if (n == only[1:] if only.startswith("=") else only in n)]
        assert names, f"--only {only!r} matches no cases"
    if start_after:
        assert start_after in names, f"{start_after!r} not in case list"
        names = names[names.index(start_after) + 1:]
    n_fail = 0
    lines = []

    def emit(line):
        lines.append(line)
        print(line, flush=True)
        with open(log_path + ".partial", "a") as f:
            f.write(line + "\n")

    def case_result(out_path, name):
        """Parse the child's own result line: (status, detail) or None.

        TIMEOUT: the child's own watchdogs (armed at 0.8x/1.0x the
        case timeout, clocks starting after interpreter startup)
        usually trip BEFORE the parent's Popen-anchored deadline — the
        child then writes its TIMEOUT line and hard-exits, and the
        parent must score it as the hang it is, not "FAIL rc=1"."""
        try:
            with open(out_path) as f:
                for ln in f.read().splitlines():
                    toks = ln.split()
                    if toks[:1] == [name] and len(toks) >= 2 and \
                            toks[1] in ("PASS", "FAIL", "TIMEOUT",
                                        "NONFINITE"):
                        return toks[1], " ".join(toks[2:])
        except OSError:
            pass
        return None

    from triton_dist_tpu.resilience import known_bad_cache
    consecutive_hangs = 0
    stopped = False
    for name in names:
        t0 = time.perf_counter()
        out_path = log_path + f".case_out.{name.replace('/', '_')}"
        with open(out_path, "w") as out:
            child = subprocess.Popen(
                [sys.executable, __file__, "--only", f"={name}",
                 "--hard-exit", "--no-preflight",
                 "--case-timeout", str(timeout_s),
                 "--log", log_path + ".case"],
                stdout=out, stderr=subprocess.STDOUT)
        hung = False
        while child.poll() is None:
            if time.perf_counter() - t0 > timeout_s:
                hung = True
                break  # abandon, never kill mid-compile
            time.sleep(2.0)
        dt = time.perf_counter() - t0
        parsed = case_result(out_path, name)
        if hung and parsed is None:
            n_fail += 1
            consecutive_hangs += 1
            if consecutive_hangs >= 2:
                # Second hang in a row: that's the TUNNEL wedged, not
                # this kernel — no known-bad record (it would blame a
                # case that never reached its compile), and no point
                # burning a timeout per remaining case.
                emit(f"{name:<28} {'TIMEOUT':<9} {dt:.0f}s second "
                     f"consecutive hang — tunnel wedged, run stops "
                     f"(no known-bad recorded for this case)")
                stopped = True
                break
            known_bad_cache().record(f"smoke:{name}", "subproc-case",
                                     "tunnel", reason="compile hang "
                                     f"abandoned after {timeout_s:.0f}s")
            emit(f"{name:<28} {'TIMEOUT':<9} {dt:.0f}s abandoned after "
                 f"{timeout_s:.0f}s (never killed; known-bad recorded; "
                 f"queue advances)")
            continue
        if parsed is not None:
            status, detail = parsed
            if hung:
                detail += " (teardown abandoned)"
        else:
            status = "PASS" if child.returncode == 0 else "FAIL"
            detail = f"rc={child.returncode}"
        # Child-detected hangs (its own watchdog tripped and it wrote
        # TIMEOUT) feed the wedged-tunnel accounting like parent-
        # detected ones; anything else resets the streak.
        consecutive_hangs = (consecutive_hangs + 1
                             if status == "TIMEOUT" else 0)
        # Forward the child's PROFILE lines (the per-case device-
        # capture evidence) into the parent report; an INVALID /
        # UNATTRIBUTED capture fails the RUN even though the case's
        # kernel passed — the parent scores cases from their result
        # line, not the child rc, so the capture contract must be
        # re-applied here.
        profile_lines = []
        try:
            with open(out_path) as f:
                profile_lines = [ln for ln in f.read().splitlines()
                                 if ln.startswith("PROFILE ")]
        except OSError:
            pass
        if not hung:
            os.unlink(out_path)
        n_fail += status != "PASS"
        emit(f"{name:<28} {status:<9} {dt:.0f}s {detail}")
        for ln in profile_lines:
            emit(ln)
            if " INVALID " in ln or " UNATTRIBUTED " in ln:
                n_fail += 1
        if consecutive_hangs >= 2:
            emit("second consecutive hang — tunnel wedged, run stops")
            stopped = True
            break
    report = "\n".join(lines + [f"TOTAL {len(names)} ops, "
                                f"{n_fail} failing"
                                + (" [STOPPED: tunnel wedged]"
                                   if stopped else "")])
    with open(log_path, "a") as f:
        f.write(report + "\n")
    print(report.splitlines()[-1])
    return 1 if n_fail else 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--log", default="tpu_smoke.log")
    ap.add_argument("--only", default=None,
                    help="substring filter on case names (=name exact)")
    ap.add_argument("--list", action="store_true",
                    help="print case names (CPU; no kernels run)")
    ap.add_argument("--subproc", action="store_true",
                    help="one subprocess per case with a hard timeout")
    ap.add_argument("--case-timeout", type=float, default=420.0,
                    help="per-case deadline (seconds): the subprocess "
                         "hard timeout under --subproc, the in-process "
                         "compile-watchdog budget otherwise; a trip "
                         "marks the case TIMEOUT, records it in the "
                         "known-bad cache, and the queue advances")
    ap.add_argument("--skip", default=None,
                    help="comma-separated exact case names to exclude "
                         "(e.g. risky never-compiled kernels, run last "
                         "separately)")
    ap.add_argument("--start-after", default=None,
                    help="resume a stopped --subproc run: skip every case "
                         "up to and including this one")
    ap.add_argument("--hard-exit", action="store_true",
                    help="os._exit after writing results (skip JAX "
                         "teardown — it can hang on a wedged tunnel)")
    ap.add_argument("--export-lint", action="store_true",
                    help="lower every case for the TPU platform on this "
                         "host (Pallas/Mosaic verifier, no execution; "
                         "works without a chip)")
    ap.add_argument("--world", type=int, default=1,
                    help="mesh size for --export-lint: verifies the "
                         "world-N ring/remote-DMA variants' Mosaic "
                         "lowering (world>1 never executes)")
    ap.add_argument("--no-preflight", action="store_true",
                    help="skip the tdt-check static-analysis preflight "
                         "(docs/analysis.md) — per-case subprocesses "
                         "use this; the parent already ran it")
    args = ap.parse_args()
    if args.world != 1:
        # Early, clear validation: the smoke shapes divide by powers of
        # two up to 8; anything else produces a wall of shape-assert
        # FAILs that read like lint regressions (review r3h finding 2).
        assert args.export_lint, "--world N>1 requires --export-lint"
        assert args.world in (2, 4, 8), (
            f"--world {args.world}: smoke shapes support 2/4/8")
    if args.list:
        sys.exit(run_smoke(None, None, list_only=True))
    with open(args.log, "w") as f:
        f.write(f"tpu_smoke @ {time.strftime('%Y-%m-%d %H:%M:%S')}\n")
    if args.subproc:
        assert not args.export_lint, (
            "--export-lint runs in-process on the CPU host; "
            "drop --subproc (no tunnel involved, nothing to isolate)")
        sys.exit(run_subproc(args.log, args.case_timeout, skip=args.skip,
                             start_after=args.start_after, only=args.only,
                             preflight=not args.no_preflight))
    if args.world > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={args.world}"
            ).strip()
    rc = run_smoke(args.log, args.only, skip=args.skip,
                   export_lint=args.export_lint, world=args.world,
                   case_timeout=args.case_timeout,
                   preflight=not args.no_preflight)
    if args.hard_exit:
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    sys.exit(rc)
