"""Benchmark entry point (driver-run on real TPU hardware).

Round-3 contract (VERDICT.md r2 "next round" 2+4): land numeric values.
Backend init is retried with backoff; every sub-benchmark failure
degrades to an ``*_error`` field captured with ``repr(e)`` (round 2's
``format_exc().splitlines()[-1]`` grabbed JAX's "internal frames
removed" footer and destroyed the diagnosis); and a ``timing_selfcheck``
calibrates the timing path against a known-FLOPs matmul so physically
impossible numbers are flagged instead of published.

What it benches (BASELINE.md north star: per-op TFLOPS + overlap
efficiency; reference headline e2e_dense.md:21):
  * ``ag_gemm``      — fused AllGather-GEMM Pallas kernel vs the XLA
    all_gather+dot baseline, TFLOPS per chip.
  * ``gemm_rs``      — fused GEMM-ReduceScatter vs XLA dot+psum_scatter.
  * ``gemm_ar``      — fused GEMM-AllReduce (decode path) at production
    width vs XLA dot+psum (VERDICT r2 next 5).
  * ``flash_decode`` — distributed split-KV decode latency at a serving
    shape vs the XLA partial-softmax baseline (VERDICT r2 next 6).
  * ``tp_mlp``       — the round-1 headline metric (fused MLP fwd ms).
On a single chip (the tunneled bench environment) the collective parts
collapse, so the numbers measure Mosaic-kernel vs XLA compute quality;
on a real slice the same code measures overlap.

Timing: each mode is timed as a self-chained step with a per-run
perturbed input (the tunnel executes lazily, dedupes unread AND repeated
results) and the per-step cost is the slope between two chained runs
(runtime/utils.perf_func_chained).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"extras"}. ``vs_baseline`` > 1.0 means the fused/Pallas path beats the
XLA baseline on the same hardware.
"""

from __future__ import annotations

import json
import os
import sys
import time

import _cache_env  # noqa: F401  (persistent compile cache; pre-jax)

os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
# Persist autotune sweeps next to the repo so later rounds (and reruns
# after a tunnel outage) skip the 20-40 s Mosaic compile per candidate.
os.environ.setdefault(
    "TDT_AUTOTUNE_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".tdt_autotune_cache.json"))


def _err(e: BaseException) -> str:
    return repr(e)[:300]


def _args_step(fn, *bigs):
    """jit ``fn(x, *bigs)`` with the big arrays passed as ARGUMENTS.

    A jitted closure embeds captured device arrays as HLO constants; on
    the tunneled backend the 128-MB KV caches / 256-MB expert weights
    made the serialized program exceed the compile server's body limit
    (``remote_compile: HTTP 413``). Passing them as jit arguments keeps
    the program parameter-only, so the payload stays small."""
    import jax
    jitted = jax.jit(fn)

    def step(x):
        return jitted(x, *bigs)
    return step


def _checkpoint_extras(extras: dict, last_done: str) -> None:
    """Stream partial results to ``TDT_BENCH_PROGRESS`` after every
    sub-benchmark.

    A 40-minute bench run through the tunnel was killed by an outer
    timeout with ALL measurements lost because the JSON line only
    prints at the end (r3); with the checkpoint file, an interrupted
    run still leaves every completed metric on disk."""
    path = os.environ.get("TDT_BENCH_PROGRESS")
    if not path:
        return
    try:
        tmp = path + ".tmp"  # atomic: a mid-write kill must not truncate
        with open(tmp, "w") as f:  # the very file this exists to protect
            json.dump({"last_done": last_done, "extras": extras}, f,
                      indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        pass


def _probe_backend_subprocess(timeout_s: float) -> bool:
    """Probe backend init in a THROWAWAY subprocess with a hard deadline.

    Two failure modes make in-process retry useless (round-1 postmortem):
    the tunneled PJRT plugin can *hang* in make_c_api_client (no
    exception ever reaches a retry loop), and jax caches backend init
    failures so a second in-process jax.devices() cannot recover. A
    subprocess gives both a kill-able deadline and a fresh cache."""
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(len(d))"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


#: Sub-benchmark execution order. Value-bearing, proven-stable parts
#: first; parts whose Mosaic compiles have historically hung or failed
#: (sp_attn, train) last so a stuck compile can only cost the tail.
_PART_ORDER = ("ag_gemm", "gemm_rs", "gemm_ar", "flash_decode",
               "moe_ag_gg", "mega", "tp_mlp", "sp_attn", "train")

#: Per-part wall deadline (seconds) in the subprocess-orchestrated mode.
#: Must exceed _init_backend's worst-case probe/backoff window (~1800 s)
#: so a tunnel that recovers mid-run is waited out instead of aborting
#: the whole bench on the first part.
_PART_DEADLINE_S = {"train": 3600.0}
_PART_DEADLINE_DEFAULT_S = 2700.0


def _run_parts_in_children(extras: dict) -> None:
    """Run every sub-benchmark as its own child process with a deadline.

    This is the default full-run mode: a train-step Mosaic compile was
    observed stuck for 30+ min through the tunnel, and an in-process
    hang would swallow ALL metrics (the JSON line only prints at the
    end). Children that blow the deadline are ABANDONED, not killed —
    SIGKILLing a client mid-compile is the known tunnel-wedge trigger
    (BENCH_NOTES_r3.md); an abandoned child either finishes harmlessly
    later or idles until round end. The run then STOPS (see the break
    below): remaining parts would only queue behind the stuck compile,
    and completed metrics must survive."""
    import subprocess
    import sys
    import tempfile
    me = os.path.abspath(__file__)
    for name in _PART_ORDER:
        fd, tmp_path = tempfile.mkstemp(suffix=f".bench_{name}.json")
        os.close(fd)
        env = dict(os.environ)
        env["TDT_BENCH_ONLY"] = name
        env["TDT_BENCH_PROGRESS"] = tmp_path
        env["TDT_BENCH_SUBPROC"] = "0"
        deadline = _PART_DEADLINE_S.get(name, _PART_DEADLINE_DEFAULT_S)
        try:
            child = subprocess.Popen(
                [sys.executable, me], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            t0 = time.monotonic()
            while child.poll() is None:
                if time.monotonic() - t0 > deadline:
                    extras[name + "_timeout_s"] = round(deadline)
                    break  # abandon, never kill mid-compile
                time.sleep(5.0)
            if child.poll() is not None and child.returncode != 0:
                # A child that died without checkpointing (segfault,
                # OOM-kill) must still leave a marker.
                extras[name + "_rc"] = child.returncode
        except OSError as e:
            extras[name + "_spawn_error"] = _err(e)
        try:
            with open(tmp_path) as f:
                part = json.load(f).get("extras", {})
            for key in ("fatal", "timing_selfcheck",
                        "timing_selfcheck_error"):
                if key in part:  # attribute generic keys to their part
                    part[f"{name}_{key}"] = part.pop(key)
            extras.update(part)
        except (OSError, ValueError):
            pass
        finally:
            if name + "_timeout_s" in extras:
                # The abandoned child will recreate this path on its
                # next checkpoint; leave it and record where it is so
                # a late finish is still collectable.
                extras[name + "_progress_path"] = tmp_path
            else:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
        _checkpoint_extras(extras, name)
        if name + "_timeout_s" in extras:
            # The tunnel is still occupied by the abandoned compile;
            # stop here so completed metrics survive (remaining parts
            # would only queue behind the stuck one).
            extras["aborted_after"] = name
            break


def _select_result(extras: dict) -> dict:
    """One definition of the headline-metric fallback order (the
    parent-orchestrated and inline tails previously carried drifting
    copies)."""
    if "ag_gemm_tflops" in extras:
        return {"metric": "ag_gemm_tflops",
                "value": extras["ag_gemm_tflops"], "unit": "TFLOPS",
                "vs_baseline": extras.get("ag_gemm_vs_xla"),
                "extras": extras}
    if "gemm_rs_tflops" in extras:
        return {"metric": "gemm_rs_tflops",
                "value": extras["gemm_rs_tflops"], "unit": "TFLOPS",
                "vs_baseline": extras.get("gemm_rs_vs_xla"),
                "extras": extras}
    if "tp_mlp_fused_ms" in extras:
        return {"metric": "tp_mlp_fused_ms",
                "value": extras["tp_mlp_fused_ms"], "unit": "ms",
                "vs_baseline": extras.get("tp_mlp_vs_xla"),
                "extras": extras}
    return {"metric": "ag_gemm_tflops", "value": None, "unit": "TFLOPS",
            "vs_baseline": None, "extras": extras}


def _init_backend(retries: int = 5, probe_timeout_s: float = 240.0,
                  backoff_s: float = 60.0):
    """Return jax.devices(), but only attempt in-process init after a
    subprocess probe has confirmed the backend actually comes up.

    ``TDT_BENCH_CPU=1`` skips the probe and pins the CPU platform via
    jax.config (which works even while a wedged axon tunnel hangs every
    devices() call — observed r3): the CPU validation path for bench's
    own code.

    Five probes with growing backoff (~15 min total): the tunnel has
    been observed to wedge for hours after a hung kernel, and a late
    recovery is worth waiting out — a null BENCH is the worst outcome.
    """
    if os.environ.get("TDT_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()
    for attempt in range(retries):
        if _probe_backend_subprocess(probe_timeout_s):
            import jax
            return jax.devices()
        if attempt < retries - 1:
            time.sleep(backoff_s * (attempt + 1))
    raise RuntimeError(
        f"backend never initialized within {retries} probe attempts")


def _bench_ag_gemm(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_ag_gemm_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))

    def make_step(impl):
        def f(a, bb):
            c = ag_gemm(a, bb, ctx, impl=impl)
            # fold C back to A's shape so the step chains; the fold cost
            # is identical across impls.
            return c[:, :k].astype(jnp.float32).astype(jnp.bfloat16) * 1e-3
        return _args_step(f, b)

    flops = 2.0 * m * k * nn  # with column sharding each chip does
    # 2*M*K*N/n flops; report per-chip TFLOPS.
    t_pallas = perf_func_chained(make_step("pallas"), a0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), a0, (8, 24))

    # Autotuned config (eager sweep caches by shape; VERDICT r1 item 5).
    import dataclasses
    from triton_dist_tpu.ops import allgather_gemm as agm
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = agm.ag_gemm(a0, b, tctx, impl="pallas")   # eager → sweep
        tuned_step = _args_step(
            lambda x, bb: (agm.ag_gemm(x, bb, tctx, impl="pallas")
                           [:, :k].astype(jnp.float32).astype(jnp.bfloat16)
                           * 1e-3), b)
        t_tuned = perf_func_chained(tuned_step, a0, (8, 24))
        key_t = next(iter(k2 for k2 in agm._TUNED
                          if k2[:2] == (m, k)), None)
        extras["ag_gemm_tuned_ms"] = round(t_tuned, 4)
        extras["ag_gemm_tuned_cfg"] = agm._TUNED.get(key_t)
        t_pallas = min(t_pallas, t_tuned)
    except Exception as e:  # noqa: BLE001
        extras["ag_gemm_tune_error"] = _err(e)

    tflops = flops / max(n, 1) / (t_pallas * 1e-3) / 1e12
    extras["ag_gemm_pallas_ms"] = round(t_pallas, 4)
    extras["ag_gemm_xla_ms"] = round(t_xla, 4)
    extras["ag_gemm_tflops"] = round(tflops, 2)
    extras["ag_gemm_vs_xla"] = round(t_xla / t_pallas, 4)
    return tflops, t_xla / t_pallas


def _bench_gemm_rs(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_gemm_rs_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    # gemm_rs maps (M, K) -> (M/w, N); chain by tiling the output back up
    # to (M, K) — identical fold cost across impls.
    def make_step(impl, c=None):
        ctx2 = ctx if c is None else c

        def f(a, bb):
            out = gemm_rs(a, bb, ctx2, impl=impl)    # (M/w, N)
            reps = (m * k) // (out.shape[0] * out.shape[1])
            full = jnp.tile(out, (max(reps, 1), 1))[:m, :k]
            return (full.astype(jnp.float32) * 1e-3).astype(jnp.bfloat16)
        return _args_step(f, b)

    t_ms = {}
    for impl in ("pallas", "xla"):
        t_ms[impl] = perf_func_chained(make_step(impl), a0, (8, 24))

    import dataclasses
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = grs.gemm_rs(a0, b, tctx, impl="pallas")   # eager → sweep
        ms_t = perf_func_chained(make_step("pallas", tctx), a0, (8, 24))
        extras["gemm_rs_tuned_ms"] = round(ms_t, 4)
        extras["gemm_rs_tuned_cfg"] = next(
            (v for kk, v in grs._TUNED.items() if kk[0] == m), None)
        t_ms["pallas"] = min(t_ms["pallas"], ms_t)
    except Exception as e:  # noqa: BLE001
        extras["gemm_rs_tune_error"] = _err(e)
    flops = 2.0 * m * k * nn
    tflops = flops / max(n, 1) / (t_ms["pallas"] * 1e-3) / 1e12
    extras["gemm_rs_pallas_ms"] = round(t_ms["pallas"], 4)
    extras["gemm_rs_xla_ms"] = round(t_ms["xla"], 4)
    extras["gemm_rs_tflops"] = round(tflops, 2)
    extras["gemm_rs_vs_xla"] = round(t_ms["xla"] / t_ms["pallas"], 4)
    return tflops, t_ms["xla"] / t_ms["pallas"]


def _bench_gemm_ar(mesh, n, on_tpu, extras):
    """Decode-path GEMM-AllReduce at production width (VERDICT r2 next 5:
    (128, 4096) x (4096, 4096) must run via the hbm path, not VMEM
    residency)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (128, 4096, 4096) if on_tpu else (16, 128, 128)
    ctx = create_gemm_rs_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(impl):
        def f(a, bb):
            out = gemm_ar(a, bb, ctx, impl=impl)     # (M, N) replicated
            return (out[:, :k].astype(jnp.float32) * 1e-3
                    ).astype(jnp.bfloat16)
        return _args_step(f, b)

    t_pallas = perf_func_chained(make_step("pallas"), a0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), a0, (8, 24))
    extras["gemm_ar_pallas_ms"] = round(t_pallas, 4)
    extras["gemm_ar_xla_ms"] = round(t_xla, 4)
    extras["gemm_ar_vs_xla"] = round(t_xla / t_pallas, 4)
    return t_pallas, t_xla / t_pallas


def _bench_flash_decode(mesh, n, on_tpu, extras):
    """Distributed split-KV GQA decode latency at a serving shape
    (VERDICT r2 next 6; reference scaling claim README.md:203-205)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        b, hq, hkv, d, t = 8, 32, 8, 128, 8192
    else:
        b, hq, hkv, d, t = 2, 8, 2, 64, 256
    ctx = create_flash_decode_context(
        mesh, "tp", interpret=None if not on_tpu else False,
        variant="tiled", t_blk=512)
    q0 = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d),
                           jnp.float32).astype(jnp.bfloat16)
    kc = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, d),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    vc = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, d),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    kv_len = jnp.int32(t - 7)

    def make_step(impl, c=None):
        def f(q, kcache, vcache, c=ctx if c is None else c):
            out = gqa_fwd_batch_decode(q, kcache, vcache, kv_len, c,
                                       impl=impl)
            return (out.astype(jnp.float32) * 0.5 + 0.5
                    ).astype(jnp.bfloat16)
        return _args_step(f, kc, vc)

    t_pallas = perf_func_chained(make_step("pallas"), q0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), q0, (8, 24))
    if on_tpu:
        # t_blk sweep (failure-isolated like the GEMM sweeps): the split
        # size trades VMEM residency against combine overhead.
        best = (t_pallas, 512)
        for t_blk in (256, 1024, 2048):
            try:
                ctx2 = create_flash_decode_context(
                    mesh, "tp", interpret=False, variant="tiled",
                    t_blk=t_blk)
                ms = perf_func_chained(make_step("pallas", ctx2),
                                      q0, (8, 24))
                if ms < best[0]:
                    best = (ms, t_blk)
            except Exception as e:  # noqa: BLE001 — per-config isolation
                extras[f"flash_decode_tblk{t_blk}_error"] = _err(e)
        extras["flash_decode_best_tblk"] = best[1]
        t_pallas = min(t_pallas, best[0])
    extras["flash_decode_pallas_ms"] = round(t_pallas, 4)
    extras["flash_decode_xla_ms"] = round(t_xla, 4)
    extras["flash_decode_vs_xla"] = round(t_xla / t_pallas, 4)
    return t_pallas, t_xla / t_pallas


def _bench_sp_attention(mesh, n, on_tpu, extras):
    """Long-context prefill attention: fused SP kernel vs XLA AG-KV
    golden (reference sp_ag_attention_inter_node.py; at world=1 this is
    the local flash-path comparison)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        b, s, hq, hkv, d = 1, 4096, 16, 8, 128
    else:
        b, s, hq, hkv, d = 1, 256, 8, 4, 32
    ctx = create_sp_attention_context(
        mesh, "tp", causal=True,
        interpret=None if not on_tpu else False)
    sh = NamedSharding(mesh, P(None, "tp"))
    q0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (b, s, hq, d),
                          jnp.float32).astype(jnp.bfloat16), sh)
    k = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                          jnp.float32).astype(jnp.bfloat16), sh)
    v = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                          jnp.float32).astype(jnp.bfloat16), sh)

    def make_step(impl):
        def f(q, kk, vv):
            out = sp_ag_attention(q, kk, vv, ctx, impl=impl)
            return (out.astype(jnp.float32) * 0.5 + 0.5
                    ).astype(jnp.bfloat16)
        return _args_step(f, k, v)

    t_fused = perf_func_chained(make_step("pallas"), q0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), q0, (8, 24))
    extras["sp_attn_fused_ms"] = round(t_fused, 4)
    extras["sp_attn_xla_ms"] = round(t_xla, 4)
    extras["sp_attn_vs_xla"] = round(t_xla / t_fused, 4)
    return t_fused, t_xla / t_fused


def _bench_ag_group_gemm(mesh, n, on_tpu, extras):
    """Fused-Pallas vs ppermute-ring AG+grouped-GEMM (VERDICT r2 next 7:
    measure both on the chip, keep whichever wins)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.group_gemm import (
        create_ag_group_gemm_context, ag_group_gemm)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn, n_exp = (2048, 4096, 4096, 8) if on_tpu else (64, 64, 128, 4)
    ctx = create_ag_group_gemm_context(mesh, "tp")
    ctx.interpret = None if not on_tpu else False
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))
    w = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (n_exp, k, nn),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, None, "tp")))
    eid = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(2), (m,), 0, n_exp,
                           jnp.int32),
        NamedSharding(mesh, P("tp")))

    def make_step(impl):
        def f(x, ww):
            c = ag_group_gemm(x, ww, eid, n_exp, ctx, impl=impl)
            return (c[:, :k].astype(jnp.float32) * 1e-3
                    ).astype(jnp.bfloat16)
        return _args_step(f, w)

    t_fused = perf_func_chained(make_step("fused"), x0, (8, 24))
    t_ring = perf_func_chained(make_step("ring"), x0, (8, 24))
    extras["moe_ag_gg_fused_ms"] = round(t_fused, 4)
    extras["moe_ag_gg_ring_ms"] = round(t_ring, 4)
    extras["moe_ag_gg_winner"] = ("fused" if t_fused <= t_ring
                                  else "ring")

    # MoE-RS: fused single kernel vs ppermute ring (same VERDICT item).
    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    topk = 2
    t_tok, inter, hid = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    mctx = create_moe_rs_context(mesh, "tp", num_experts=n_exp, topk=topk)
    mctx.interpret = None if not on_tpu else False
    act0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (t_tok * topk, inter),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    wdn = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(4), (n_exp, inter, hid),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    eid2 = jax.random.randint(jax.random.PRNGKey(5), (t_tok * topk,), 0,
                              n_exp, jnp.int32)
    wts = jax.nn.softmax(jax.random.normal(
        jax.random.PRNGKey(6), (t_tok, topk), jnp.float32))

    def make_mrs(impl):
        def f(a, wd):
            out = moe_reduce_rs(a, wd, eid2, wts, mctx, impl=impl)
            reps = (t_tok * topk * inter) // (out.shape[0] * out.shape[1])
            full = jnp.tile(out, (max(reps, 1), 1))[:t_tok * topk, :inter]
            return (full.astype(jnp.float32) * 1e-3).astype(jnp.bfloat16)
        return _args_step(f, wdn)

    t_mf = perf_func_chained(make_mrs("fused"), act0, (8, 24))
    t_mr = perf_func_chained(make_mrs("ring"), act0, (8, 24))
    extras["moe_rs_fused_ms"] = round(t_mf, 4)
    extras["moe_rs_ring_ms"] = round(t_mr, 4)
    extras["moe_rs_winner"] = "fused" if t_mf <= t_mr else "ring"
    return min(t_fused, t_ring), t_ring / t_fused


def _bench_mega_vs_engine(mesh, n, on_tpu, extras):
    """Megakernel (one fused jit program per decode step) vs the plain
    engine decode step (VERDICT r2 L8 note: 'no perf evidence vs
    engine'; reference mega_triton_kernel.md:30-39 decode latencies)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.mega import MegaQwen3
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        cfg = ModelConfig(hidden_size=2048, intermediate_size=8192,
                          num_hidden_layers=4, num_attention_heads=16,
                          num_key_value_heads=8, head_dim=128,
                          vocab_size=32768, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        b = 8
    else:
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, head_dim=64,
                          vocab_size=256, max_position_embeddings=64,
                          dtype=jnp.bfloat16)
        b = 2
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas")
    params = model.init(jax.random.PRNGKey(0))
    kv = KVCacheManager(cfg.num_hidden_layers, b,
                        cfg.max_position_embeddings,
                        cfg.num_key_value_heads, cfg.head_dim, mesh=mesh,
                        axis="tp", dtype=cfg.dtype)
    caches = kv.init()
    # The chain carry must be FLOAT: perturb_input only perturbs
    # floating leaves, and an int token chain would replay identical
    # computations the tunnel dedupes (code-review r3c finding 1).
    x0 = jnp.ones((b, 1), jnp.float32)
    mega = MegaQwen3(model, decode_mode="gemm_ar")

    def make_step(use_mega):
        def f(x, p, cc):
            token = (jnp.abs(x) * 997).astype(jnp.int32) % cfg.vocab_size
            if use_mega:
                logits, _ = mega.step(p, token, cc, 4)
            else:
                logits, _ = model.forward(p, token, cc,
                                          jnp.int32(4), mode="gemm_ar")
            return jnp.mean(logits[:, -1].astype(jnp.float32), axis=-1,
                            keepdims=True)
        return _args_step(f, params, caches)

    t_mega = perf_func_chained(make_step(True), x0, (8, 24))
    t_engine = perf_func_chained(make_step(False), x0, (8, 24))
    extras["mega_step_ms"] = round(t_mega, 4)
    extras["engine_step_ms"] = round(t_engine, 4)
    extras["mega_vs_engine"] = round(t_engine / t_mega, 4)

    # Continuous-batching hot path: the stream decode step runs every
    # row at its OWN cache position (per-row scatter writes + per-row
    # masks/rope — Engine.serve_stream). Its cost vs the plain
    # uniform-offset step quantifies the scheduling flexibility's price.
    offsets0 = jnp.full((b,), 4, jnp.int32)

    def stream_step(x, p, cc):
        token = (jnp.abs(x) * 997).astype(jnp.int32) % cfg.vocab_size
        logits, _ = model.forward(p, token, cc, offsets0 + token[:, 0] % 2,
                                  mode="gemm_ar")
        return jnp.mean(logits[:, -1].astype(jnp.float32), axis=-1,
                        keepdims=True)

    t_stream = perf_func_chained(_args_step(stream_step, params, caches),
                                 x0, (8, 24))
    extras["stream_step_ms"] = round(t_stream, 4)
    extras["stream_vs_engine_step"] = round(t_engine / t_stream, 4)
    return t_mega, t_engine / t_mega


def _bench_tp_mlp(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        m, hidden, inter = 2048, 4096, 12288 // max(n, 8) * n
        iters = (16, 48)
    else:
        m, hidden, inter = 256, 256, 512
        iters = (2, 4)

    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(mode):
        def f(x, p):
            y = mlp(p, x, mode=mode).astype(jnp.float32)
            scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
            return (y * scale).astype(jnp.bfloat16)
        return _args_step(f, params)

    t_fused = perf_func_chained(make_step("ag_rs"), x0, iters)
    t_base = perf_func_chained(make_step("xla"), x0, iters)
    extras["tp_mlp_fused_ms"] = round(t_fused, 4)
    extras["tp_mlp_xla_ms"] = round(t_base, 4)
    extras["tp_mlp_vs_xla"] = round(t_base / t_fused, 4)

    if on_tpu:
        # Realistic per-chip width (the reference's MLP bench runs
        # ~3456 per GPU — e2e_dense.md:21; the primary line above keeps
        # per-chip 1536 for cross-round comparability).
        mlp_big = TPMLP(hidden, 3072 * max(n, 1), mesh=mesh, axis="tp",
                        dtype=jnp.bfloat16)
        params_b = mlp_big.init(jax.random.PRNGKey(2))

        def make_step_big(mode):
            def f(x, p):
                y = mlp_big(p, x, mode=mode).astype(jnp.float32)
                scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
                return (y * scale).astype(jnp.bfloat16)
            return _args_step(f, params_b)

        tb_f = perf_func_chained(make_step_big("ag_rs"), x0, iters)
        tb_x = perf_func_chained(make_step_big("xla"), x0, iters)
        extras["tp_mlp_big_fused_ms"] = round(tb_f, 4)
        extras["tp_mlp_big_xla_ms"] = round(tb_x, 4)
        extras["tp_mlp_big_vs_xla"] = round(tb_x / tb_f, 4)
    return t_fused, t_base / t_fused


def _bench_train(mesh, n, on_tpu, extras):
    """Training-step throughput (beyond-reference: the reference is
    inference-only, SURVEY §2.9). Times the fused ag_rs train step —
    whose backward rides the transpose fused kernels (ops/autodiff.py)
    — against the xla-collective baseline; reports tokens/s."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.train import make_train_step
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        cfg = ModelConfig(hidden_size=2048, intermediate_size=8192,
                          num_hidden_layers=4, num_attention_heads=16,
                          num_key_value_heads=8, head_dim=128,
                          vocab_size=32768, max_position_embeddings=1024,
                          dtype=jnp.bfloat16)
        b, s, iters = 4, 512, (4, 12)
    else:
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, head_dim=64,
                          vocab_size=256, max_position_embeddings=64,
                          dtype=jnp.float32)
        b, s, iters = 2, 8, (2, 4)
    batch = {"input_ids": jax.random.randint(
        jax.random.PRNGKey(7), (b, s), 0, cfg.vocab_size, jnp.int32)}

    times = {}
    for key, mode, impl in (("fused", "ag_rs", "pallas"),
                            ("xla", "xla", "xla")):
        model = DenseLLM(cfg, mesh=mesh, axis="tp", impl=impl,
                         fwd_mode=mode)
        params = model.init(jax.random.PRNGKey(0))
        # donate=False: the perf chain re-perturbs the same initial
        # buffers across runs, which donation would invalidate.
        run_step, init_opt = make_train_step(model, mode=mode,
                                             donate=False)
        opt0 = init_opt(params)

        def step(carry):
            p, o = carry
            p, o, _ = run_step(p, o, batch)
            return (p, o)

        times[key] = perf_func_chained(step, (params, opt0), iters)

    extras["train_fused_ms"] = round(times["fused"], 4)
    extras["train_xla_ms"] = round(times["xla"], 4)
    extras["train_vs_xla"] = round(times["xla"] / times["fused"], 4)
    extras["train_tokens_per_s"] = round(b * s / times["fused"] * 1e3, 1)
    return times["fused"], times["xla"] / times["fused"]


def main():
    extras: dict = {}
    # Clear any stale checkpoint so a run that dies before its first
    # sub-benchmark can't pass off the previous run's metrics as its own.
    _checkpoint_extras(extras, "init")
    result = {"metric": "ag_gemm_tflops", "value": None, "unit": "TFLOPS",
              "vs_baseline": None, "extras": extras}
    only_env = [s for s in os.environ.get("TDT_BENCH_ONLY", "").split(",")
                if s]
    if not only_env and os.environ.get("TDT_BENCH_SUBPROC", "1") != "0":
        # (TDT_BENCH_CPU passes through to the children, so the whole
        # orchestration path is validatable off-tunnel.)
        # Full run: orchestrate children; the parent never touches the
        # tunnel so a hung Mosaic compile cannot take down the run.
        _run_parts_in_children(extras)
        print(json.dumps(_select_result(extras)))
        return
    try:
        import numpy as np
        devices = _init_backend()
        import jax
        from jax.sharding import Mesh
        from triton_dist_tpu.runtime.platform import is_tpu
        on_tpu = is_tpu()
        n = len(devices) if on_tpu else 1
        mesh = Mesh(np.array(devices[:n]), ("tp",))
        extras["n_devices"] = n
        extras["device_kind"] = getattr(devices[0], "device_kind", "?")

        if on_tpu:
            try:
                from triton_dist_tpu.runtime.utils import timing_selfcheck
                extras["timing_selfcheck"] = timing_selfcheck()
            except Exception as e:  # noqa: BLE001
                extras["timing_selfcheck_error"] = _err(e)

        # TDT_BENCH_ONLY: comma-separated sub-benchmark names — lets an
        # operator (or a babysitting script) run each part in its own
        # short-lived process on the flaky tunnel, so one hung Mosaic
        # compile can't take the other metrics down with it.
        benches = (
            ("ag_gemm", lambda: _bench_ag_gemm(mesh, n, on_tpu, extras)),
            ("gemm_rs", lambda: _bench_gemm_rs(mesh, n, on_tpu, extras)),
            ("gemm_ar", lambda: _bench_gemm_ar(mesh, n, on_tpu, extras)),
            ("flash_decode",
             lambda: _bench_flash_decode(mesh, n, on_tpu, extras)),
            ("sp_attn",
             lambda: _bench_sp_attention(mesh, n, on_tpu, extras)),
            ("moe_ag_gg",
             lambda: _bench_ag_group_gemm(mesh, n, on_tpu, extras)),
            ("mega",
             lambda: _bench_mega_vs_engine(mesh, n, on_tpu, extras)),
            ("tp_mlp", lambda: _bench_tp_mlp(mesh, n, on_tpu, extras)),
            ("train", lambda: _bench_train(mesh, n, on_tpu, extras)),
        )
        assert {b[0] for b in benches} == set(_PART_ORDER), \
            "benches tuple and _PART_ORDER drifted"
        only = only_env
        bad = [s for s in only if s not in {b[0] for b in benches}]
        if bad:  # a typo must not turn into a silently empty bench;
            # SystemExit bypasses the blanket except below → rc != 0.
            raise SystemExit(
                f"unknown TDT_BENCH_ONLY entries {bad}; "
                f"known: {[b[0] for b in benches]}")
        for name, fn in benches:
            if only and name not in only:
                continue
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — partial over rc!=0
                extras[name + "_error"] = _err(e)
            _checkpoint_extras(extras, name)

        result = _select_result(extras)
    except Exception as e:  # noqa: BLE001 — emit partial JSON, never rc!=0
        extras["fatal"] = _err(e)
        _checkpoint_extras(extras, "fatal")

    print(json.dumps(result))
    if only_env:
        # Child mode (one sub-benchmark per process): hard-exit to skip
        # JAX backend teardown. Teardown waits on the tunnel and has
        # been observed to linger minutes-to-forever on a wedged remote
        # (tpu_smoke 07-31); results are checkpointed + printed already.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


if __name__ == "__main__":
    main()
