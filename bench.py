"""Benchmark entry point (driver-run on real TPU hardware).

Benches the flagship fused TP-MLP forward (AG-GEMM + GEMM-RS collective
matmul path) against the unfused XLA baseline — the reference's headline
e2e MLP benchmark (docs/getting-started/e2e/e2e_dense.md:21, M=2048:
0.885 ms fused vs 1.077 ms torch on 8×H800).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup of the fused path over the XLA baseline on
the same hardware (>1.0 is a win; the reference's own headline ratio for
this shape is 1.216×).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def main():
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.platform import is_tpu
    from triton_dist_tpu.runtime.utils import perf_func

    devices = jax.devices()
    on_tpu = is_tpu()
    # Bench over every real chip available; CI/laptops fall back to a single
    # (interpreted) device so the script still produces a line.
    n = len(devices) if on_tpu else 1
    mesh = Mesh(np.array(devices[:n]), ("tp",))

    if on_tpu:
        # Shapes sized so the whole-operand-in-VMEM kernels fit ~16 MB/core
        # VMEM; the HBM-tiled kernel variants will lift this to the
        # reference's M=2048/H=4096/I=12288 headline shape.
        m, hidden, inter = 1024, 1024, 1024
        iters, warmup = 20, 5
    else:
        m, hidden, inter = 256, 256, 512
        iters, warmup = 2, 1

    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    fused = jax.jit(lambda p, x: mlp(p, x, mode="ag_rs"))
    baseline = jax.jit(lambda p, x: mlp(p, x, mode="xla"))

    _, t_fused_ms = perf_func(lambda: fused(params, x), iters, warmup)
    _, t_base_ms = perf_func(lambda: baseline(params, x), iters, warmup)

    print(json.dumps({
        "metric": "tp_mlp_fused_ms",
        "value": round(t_fused_ms, 4),
        "unit": "ms",
        "vs_baseline": round(t_base_ms / t_fused_ms, 4),
    }))


if __name__ == "__main__":
    main()
