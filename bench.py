"""Benchmark entry point (driver-run on real TPU hardware).

Benches the flagship fused TP-MLP forward (AG-GEMM + GEMM-RS collective
matmul path) against the unfused XLA baseline — the reference's headline
e2e MLP benchmark (docs/getting-started/e2e/e2e_dense.md:21, M=2048:
0.885 ms fused vs 1.077 ms torch on 8×H800).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup of the fused path over the XLA baseline on
the same hardware (>1.0 is a win; the reference's own headline ratio for
this shape is 1.216×).
"""

from __future__ import annotations

import json
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _time_fn(fn, *args, warmup=3, iters=20):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def main():
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.platform import is_tpu

    devices = jax.devices()
    # Bench over every real chip available; CI/laptops fall back to a single
    # (interpreted) device so the script always produces a line.
    n = len(devices) if is_tpu() else 1
    mesh = Mesh(np.array(devices[:n]), ("tp",))

    m, hidden, inter = 2048, 4096, 12288
    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    fused = jax.jit(lambda p, x: mlp(p, x, mode="ag_rs"))
    baseline = jax.jit(lambda p, x: mlp(p, x, mode="xla"))

    t_fused = _time_fn(fused, params, x)
    t_base = _time_fn(baseline, params, x)

    print(json.dumps({
        "metric": "tp_mlp_fused_ms",
        "value": round(t_fused * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(t_base / t_fused, 4),
    }))


if __name__ == "__main__":
    main()
