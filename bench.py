"""Benchmark entry point (driver-run on real TPU hardware).

Benches the flagship fused TP-MLP forward (AG-GEMM + GEMM-RS collective
matmul path) against the unfused XLA baseline — the reference's headline
e2e MLP benchmark (docs/getting-started/e2e/e2e_dense.md:21, M=2048:
0.885 ms fused vs 1.077 ms torch on 8×H800).

Timing methodology: the real-TPU environment here is a *tunneled* single
chip that executes lazily and dedupes unread results, so each mode is
timed as a self-chained step (``x = mlp(x)`` with a bounded renorm, the
renorm cost identical in both modes) and the per-step cost is the slope
between two chained runs (runtime/utils.perf_func_chained).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup of the fused path over the XLA baseline on
the same hardware (>1.0 is a win; the reference's own headline ratio for
this class of shape is 1.216×).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def main():
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.platform import is_tpu
    from triton_dist_tpu.runtime.utils import perf_func_chained

    devices = jax.devices()
    on_tpu = is_tpu()
    # Bench over every real chip available; CI/laptops fall back to a single
    # (interpreted) device so the script still produces a line.
    n = len(devices) if on_tpu else 1
    mesh = Mesh(np.array(devices[:n]), ("tp",))

    if on_tpu:
        # Reference-headline-class shape (e2e_dense.md:21); the hbm kernel
        # variant streams K/M tiles so VMEM no longer caps the shape.
        m, hidden, inter = 2048, 4096, 12288 // max(n, 8) * n
        iters = (16, 48)
    else:
        m, hidden, inter = 256, 256, 512
        iters = (2, 4)

    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(mode):
        @jax.jit
        def step(x):
            y = mlp(params, x, mode=mode).astype(jnp.float32)
            # bounded renorm so the chain can't overflow bf16; identical
            # cost in both modes.
            scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
            return (y * scale).astype(jnp.bfloat16)
        return step

    t_fused_ms = perf_func_chained(make_step("ag_rs"), x0, iters)
    t_base_ms = perf_func_chained(make_step("xla"), x0, iters)

    print(json.dumps({
        "metric": "tp_mlp_fused_ms",
        "value": round(t_fused_ms, 4),
        "unit": "ms",
        "vs_baseline": round(t_base_ms / t_fused_ms, 4),
    }))


if __name__ == "__main__":
    main()
