"""Benchmark entry point (driver-run on real TPU hardware).

Round-4 contract (VERDICT.md r3 "next round" 1+2): the bench must be
**un-losable** and its numbers **arithmetically self-consistent**.

Un-losable (r3 failed with rc=124 and an empty tail):
  * A GLOBAL WALL BUDGET (``TDT_BENCH_BUDGET_S``, default 1500 s) far
    under any plausible driver timeout; parts that don't fit are
    recorded as ``skipped_budget`` instead of running into the knife.
  * The backend is probed in a throwaway subprocess with a HARD
    DEADLINE before anything touches the tunnel; on failure the bench
    prints a JSON line (carrying any prior checkpointed metrics,
    clearly labeled ``prior_run``) and exits 0.
  * After EVERY completed sub-benchmark the parent prints a complete
    cumulative result JSON line to stdout AND checkpoints it to disk —
    a kill at any moment leaves every completed metric in the captured
    tail (the last parseable line is always the most complete).
  * Each sub-benchmark runs in its own child process with a deadline;
    a child that blows it is ABANDONED, not killed (SIGKILL mid-compile
    is the known tunnel-wedge trigger, BENCH_NOTES_r3.md), and the run
    stops so completed metrics survive.

Self-consistent (r3's hand-kept notes had ms/TFLOPS disagreeing 2x):
  * every ``*_tflops`` is recomputed from its ``*_ms`` + recorded
    ``*_flops`` at finalize; mismatches land in ``arith_bad``.
  * same-shape XLA baselines are cross-checked: ag_gemm's and
    gemm_rs's world=1 baselines are the same matmul and must agree
    within 1.5x of each other AND of ``timing_selfcheck.calib_ms``
    (the identical-shape plain dot); disagreements are flagged
    ``baseline_anomaly`` so no ``vs_xla`` ratio can silently ride a
    pessimized baseline (r3 weak-2: a 3.5x baseline split produced a
    fake 7.38x win).

What it benches (BASELINE.md north star; reference e2e_dense.md:21-38):
  ag_gemm / gemm_rs / gemm_ar / flash_decode / tp_mlp (the contract
  metrics), then layer_8b / layer_32b (one decoder layer at Qwen3-8B /
  -32B per-chip TP8 slice dims — reference e2e table rows), overlap
  (ag_gemm DMA-under-MXU proxy), moe_ag_gg, mega (incl. 32-layer deep
  config), serving (continuous-batching scheduler vs serialized lock,
  8 concurrent clients — valid on the CPU tier), serving_mega (mega vs
  plain decode path through the SAME scheduler — CPU-valid parity
  harness), serving_spec (n-gram speculative decoding on vs off through
  the SAME scheduler on a repetition-friendly workload — CPU-valid:
  both paths run the identical model, so the ratio prices tokens per
  step), serving_fleet (TWO in-process ModelServer replicas behind a
  client-side round-robin fanout vs one replica of the same config —
  the first measured multi-replica number, with fleet-merged
  bucket-summed TTFT/TPOT percentiles, ISSUE 14), serving_router
  (THREE replicas behind the fault-tolerant RouterServer vs direct
  round-robin, then the chaos acceptance scenario: one replica killed
  mid-window → zero client-visible failures, failovers recorded, down
  detected within the configured age — CPU-valid, ISSUE 15),
  serving_history (the SAME served workload with the obs.history
  sampler off vs on — prices the history plane's overhead; the on-leg
  must stay within the BASELINE.json floor of the off-leg, and its
  sampled series snapshot is embedded for the report, ISSUE 16), prefix (shared-preamble
  clients, prefix cache warm vs cold — also CPU-valid), sp_attn, train. On a single chip the collective parts
  collapse, so the numbers measure Mosaic-kernel vs XLA compute
  quality; on a real slice the same code measures overlap.

Timing: each mode is timed as a self-chained step with a per-run
perturbed input (the tunnel executes lazily, dedupes unread AND
repeated results) and the per-step cost is the slope between two
chained runs (runtime/utils.perf_func_chained).

Prints cumulative JSON lines: {"metric", "value", "unit",
"vs_baseline", "extras"}; the LAST line is the final result.
``vs_baseline`` > 1.0 means the fused/Pallas path beats the XLA
baseline on the same hardware.
"""

from __future__ import annotations

import json
import os
import sys
import time

import _cache_env  # noqa: F401  (persistent compile cache; pre-jax)

os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
# Persist autotune sweeps next to the repo so later rounds (and reruns
# after a tunnel outage) skip the 20-40 s Mosaic compile per candidate.
os.environ.setdefault(
    "TDT_AUTOTUNE_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".tdt_autotune_cache.json"))
def _resilience_env() -> None:
    """Bench-run resilience posture (called from main(), NOT at import
    — tests import this module and must not inherit these settings).

    The bench MEASURES the fused kernels the resilience router
    consults BASELINE ratios about — routing a bench call to its XLA
    fallback would make every *_vs_xla ratio silently measure XLA vs
    XLA (= 1.0) and poison the very data the router runs on. Force the
    fused path; the per-part subprocess deadlines still bound any
    compile hang, and watchdog trips land in the known-bad cache at
    its DEFAULT path — deliberately not a bench-local file, so a hang
    found here protects every later process on this machine (serving,
    smoke reruns) that reads the same default. Children inherit the
    flag via os.environ."""
    os.environ.setdefault("TDT_FORCE_FUSED", "1")

_T0 = time.monotonic()


def _budget_s() -> float:
    return float(os.environ.get("TDT_BENCH_BUDGET_S", "1500"))


def _remaining_s() -> float:
    return _budget_s() - (time.monotonic() - _T0)


def _err(e: BaseException) -> str:
    return repr(e)[:300]


def _args_step(fn, *bigs):
    """jit ``fn(x, *bigs)`` with the big arrays passed as ARGUMENTS.

    A jitted closure embeds captured device arrays as HLO constants; on
    the tunneled backend the 128-MB KV caches / 256-MB expert weights
    made the serialized program exceed the compile server's body limit
    (``remote_compile: HTTP 413``). Passing them as jit arguments keeps
    the program parameter-only, so the payload stays small."""
    import jax
    jitted = jax.jit(fn)

    def step(x):
        return jitted(x, *bigs)
    return step


def _progress_path() -> str:
    return os.environ.get(
        "TDT_BENCH_PROGRESS",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_progress_latest.json"))


def _checkpoint_extras(extras: dict, last_done: str) -> None:
    """Persist partial results after every sub-benchmark (r3: a killed
    40-min run lost ALL measurements because JSON only printed at the
    end)."""
    path = _progress_path()
    try:
        tmp = path + ".tmp"  # atomic: a mid-write kill must not truncate
        with open(tmp, "w") as f:  # the very file this exists to protect
            json.dump({"last_done": last_done, "ts": time.time(),
                       "extras": extras}, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError:
        pass


def _emit(extras: dict) -> None:
    """Print the cumulative result as a complete JSON line NOW — the
    driver's tail capture then always holds every completed metric,
    whatever happens next."""
    print(json.dumps(_select_result(extras)), flush=True)


def _probe_backend_subprocess(timeout_s: float) -> bool:
    """Probe backend init in a THROWAWAY subprocess with a hard deadline.

    Two failure modes make in-process retry useless (round-1
    postmortem): the tunneled PJRT plugin can *hang* in
    make_c_api_client (no exception ever reaches a retry loop), and jax
    caches backend init failures so a second in-process jax.devices()
    cannot recover. A subprocess gives both a kill-able deadline and a
    fresh cache."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(len(d))"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


#: Sub-benchmark execution order. The contract metrics (VERDICT r3
#: next-1 "done =" list) first; parts whose Mosaic compiles have
#: historically hung or failed (sp_attn, train) last so a stuck compile
#: can only cost the tail.
_PART_ORDER = ("ag_gemm", "gemm_rs", "gemm_ar", "flash_decode", "tp_mlp",
               "layer_8b", "layer_32b", "overlap", "moe_ag_gg", "mega",
               "serving", "serving_mega", "serving_spec",
               "serving_fleet", "serving_router", "serving_history",
               "serving_disagg", "prefix", "sp_attn", "train")

#: Sweep-heavy parts get longer deadlines: ag_gemm/gemm_rs autotune
#: 6-8 candidates at ~25 s Mosaic compile each on a COLD cache (the
#: r5 headline-first queue hits exactly that), and a legitimate sweep
#: must not be mistaken for a wedge and stop the run.
#: (r5 second queue: tables are tier-capped at 5+4 entries, ~30 s cold
#: Mosaic compile each; tp_mlp sweeps TWO swiglu shapes. sp_attn's
#: fused kernel took ~90 s to its round-5 compile VERDICT and the part
#: compiles fused + xla cold; mega's deep-32 fused program is the
#: largest single compile in the bench.)
_PART_DEADLINE_S = {"train": 480.0, "mega": 900.0, "ag_gemm": 900.0,
                    "gemm_rs": 900.0, "tp_mlp": 1000.0,
                    "flash_decode": 480.0, "sp_attn": 700.0}
_PART_DEADLINE_DEFAULT_S = 360.0


def _run_parts_in_children(extras: dict) -> None:
    """Run every sub-benchmark as its own child process with a deadline,
    under the global wall budget.

    Children that blow the deadline are ABANDONED, not killed —
    SIGKILLing a client mid-compile is the known tunnel-wedge trigger
    (BENCH_NOTES_r3.md); an abandoned child either finishes harmlessly
    later or idles until round end. The run then STOPS (remaining parts
    would only queue behind the stuck compile) with everything
    completed so far already printed and checkpointed."""
    import subprocess
    import tempfile
    me = os.path.abspath(__file__)
    # TDT_BENCH_PARTS: comma-separated subset of _PART_ORDER for the
    # PARENT orchestrator (per-part child isolation preserved, unlike
    # TDT_BENCH_ONLY which runs inline). Lets the hardware watcher
    # queue a short headline-only bench at position 1 (VERDICT r4
    # next-1) without giving up the abandon-don't-kill machinery.
    parts_env = [s for s in os.environ.get("TDT_BENCH_PARTS", "").split(",")
                 if s]  # validated up front in main()
    part_order = tuple(p for p in _PART_ORDER
                       if not parts_env or p in parts_env)
    for name in part_order:
        budget_left = _remaining_s()
        # A child pays up to ~180 s of backend-init (two 75 s probes +
        # backoff) before benching; spawning it with less would expire
        # the deadline during init and fake a wedge (review r4a-3).
        if budget_left < 250.0:
            extras.setdefault("skipped_budget", []).append(name)
            continue
        part_max = _PART_DEADLINE_S.get(name, _PART_DEADLINE_DEFAULT_S)
        deadline = min(part_max, budget_left - 45.0)
        budget_clamped = deadline < part_max
        fd, tmp_path = tempfile.mkstemp(suffix=f".bench_{name}.json")
        os.close(fd)
        env = dict(os.environ)
        env["TDT_BENCH_ONLY"] = name
        env["TDT_BENCH_PROGRESS"] = tmp_path
        env["TDT_BENCH_SUBPROC"] = "0"
        try:
            child = subprocess.Popen(
                [sys.executable, me], env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            t0 = time.monotonic()
            while child.poll() is None:
                if time.monotonic() - t0 > deadline:
                    extras[name + "_timeout_s"] = round(deadline)
                    break  # abandon, never kill mid-compile
                time.sleep(2.0)
            if child.poll() is not None and child.returncode != 0:
                # A child that died without checkpointing (segfault,
                # OOM-kill) must still leave a marker.
                extras[name + "_rc"] = child.returncode
        except OSError as e:
            extras[name + "_spawn_error"] = _err(e)
        try:
            with open(tmp_path) as f:
                part = json.load(f).get("extras", {})
            if "fatal" in part:  # attribute to its part
                part[f"{name}_fatal"] = part.pop("fatal")
            for key in ("timing_selfcheck", "timing_selfcheck_error"):
                # the selfcheck is only computed in the ag_gemm child;
                # keep it unprefixed there (finalize reads it).
                if key in part and name != "ag_gemm":
                    part[f"{name}_{key}"] = part.pop(key)
            tel = part.pop("telemetry", None)
            if tel:
                # Each child carries its own process-local telemetry
                # snapshot; the parent runs the same merge rank-0 would
                # across hosts (counters/histograms add, gauges max)
                # instead of letting the last child win. Sampled
                # request waterfalls are metadata merge_snapshots
                # drops — union them back by hand.
                prev = extras.get("telemetry")
                wf = {**((prev or {}).get("waterfalls") or {}),
                      **(tel.get("waterfalls") or {})}
                # The fleet-merged snapshot (serving_fleet child) is
                # metadata merge_snapshots drops, like the waterfalls;
                # ditto the router-status snapshot (serving_router).
                fleet = (tel.get("fleet")
                         or (prev or {}).get("fleet"))
                router_snap = (tel.get("router")
                               or (prev or {}).get("router"))
                hist_snap = (tel.get("history")
                             or (prev or {}).get("history"))
                try:
                    from triton_dist_tpu.obs import merge_snapshots
                    extras["telemetry"] = merge_snapshots([prev, tel])
                    if wf:
                        extras["telemetry"]["waterfalls"] = wf
                    if fleet:
                        extras["telemetry"]["fleet"] = fleet
                    if router_snap:
                        extras["telemetry"]["router"] = router_snap
                    if hist_snap:
                        extras["telemetry"]["history"] = hist_snap
                except Exception:  # noqa: BLE001 — telemetry is extra
                    # Keep what already accumulated over prior parts;
                    # only seed from this child when there is nothing.
                    extras.setdefault("telemetry", tel)
            extras.update(part)
        except (OSError, ValueError):
            pass
        finally:
            if name + "_timeout_s" in extras:
                # The abandoned child will recreate this path on its
                # next checkpoint; leave it and record where it is so
                # a late finish is still collectable.
                extras[name + "_progress_path"] = tmp_path
            else:
                try:
                    os.unlink(tmp_path)
                except OSError:
                    pass
        _finalize_checks(extras)
        _checkpoint_extras(extras, name)
        _emit(extras)
        if name + "_timeout_s" in extras:
            # The run stops either way (the abandoned child still holds
            # the backend), but the evidence must say WHY: a deadline
            # clamped by the remaining budget is ordinary budget
            # exhaustion, not a wedge signal (review r4b-3).
            extras["aborted_after"] = name
            if budget_clamped:
                extras[name + "_timeout_budget_clamped"] = True
                extras["aborted_reason"] = "budget_exhausted"
                extras.setdefault("skipped_budget", []).extend(
                    p for p in part_order[part_order.index(name) + 1:])
            else:
                extras["aborted_reason"] = "possible_wedge"
            break


#: (flops_key, ms_key, tflops_key) triples the finalize pass verifies.
_ARITH_TRIPLES = (
    ("ag_gemm_flops", "ag_gemm_pallas_ms", "ag_gemm_tflops"),
    ("gemm_rs_flops", "gemm_rs_pallas_ms", "gemm_rs_tflops"),
)


def _finalize_checks(extras: dict) -> None:
    """Arithmetic + baseline consistency gates (VERDICT r3 next-2).

    ``arith_bad`` lists any (ms, TFLOPS) pair that disagrees with its
    recorded flops — by construction both come from one measurement, so
    an entry here means the bench code itself regressed. The baseline
    cross-check compares the two same-matmul world=1 XLA baselines with
    each other and with the timing_selfcheck's plain-dot calibration at
    the identical (2048x4096)@(4096x4096) bf16 shape."""
    bad = []
    for fk, mk, tk in _ARITH_TRIPLES:
        if fk in extras and mk in extras and tk in extras:
            n = max(int(extras.get("n_devices", 1)), 1)
            implied = (float(extras[fk]) / n
                       / (float(extras[mk]) * 1e-3) / 1e12)
            # 2% relative + the 2-decimal rounding granularity of the
            # reported value (CPU-validation tflops round to 0.00).
            if abs(implied - float(extras[tk])) > 0.02 * implied + 0.005:
                bad.append({"key": tk, "reported": extras[tk],
                            "implied_by_ms": round(implied, 2)})
    extras["arith_bad"] = bad
    extras["arith_ok"] = not bad

    ag = extras.get("ag_gemm_xla_ms")
    rs = extras.get("gemm_rs_xla_ms")
    sc = extras.get("timing_selfcheck") or {}
    calib = sc.get("calib_ms")
    anomalies = []
    if ag and rs:
        r = max(ag, rs) / min(ag, rs)
        extras["baseline_xla_ratio"] = round(r, 3)
        # Fires on CPU runs too since r5: with min-of-5 windowed timing
        # (perf_func_chained) the toy-shape pair agrees within ~1.05x
        # unloaded / 1.36x under bursty load on the 1-core host, so
        # >1.5x is a real signal, not scheduler noise (docs/perf.md
        # "2.845x ... root cause").
        if r > 1.5:
            anomalies.append(f"ag_gemm_xla {ag} vs gemm_rs_xla {rs}: "
                             f"same matmul, {r:.2f}x apart")
    # calib_ms times the FULL matmul on one chip, while the baselines
    # shard it over the mesh — the comparison is only apples-to-apples
    # at world=1 (the bench-tunnel environment).
    if int(extras.get("n_devices", 1)) == 1:
        for key, val in (("ag_gemm_xla_ms", ag), ("gemm_rs_xla_ms", rs)):
            if val and calib:
                # The baseline adds a chain-fold (slice+scale+cast) on
                # top of the calibration dot, so allow 1.6x headroom;
                # beyond that the baseline is pessimized and its vs_xla
                # is bogus.
                if val > 1.6 * calib or val < calib / 1.6:
                    anomalies.append(f"{key} {val} vs calib dot {calib}")
    extras["baseline_anomaly"] = anomalies or None


def _select_result(extras: dict) -> dict:
    """One definition of the headline-metric fallback order."""
    for metric, unit, vs in (
            ("ag_gemm_tflops", "TFLOPS", "ag_gemm_vs_xla"),
            ("gemm_rs_tflops", "TFLOPS", "gemm_rs_vs_xla"),
            ("tp_mlp_fused_ms", "ms", "tp_mlp_vs_xla")):
        if metric in extras:
            return {"metric": metric, "value": extras[metric],
                    "unit": unit, "vs_baseline": extras.get(vs),
                    "extras": extras}
    return {"metric": "ag_gemm_tflops", "value": None, "unit": "TFLOPS",
            "vs_baseline": None, "extras": extras}


def _init_backend(probe_timeout_s: float = 75.0, retries: int = 2,
                  backoff_s: float = 30.0):
    """Return jax.devices(), but only attempt in-process init after a
    subprocess probe confirmed the backend actually comes up.

    ``TDT_BENCH_CPU=1`` skips the probe and pins the CPU platform via
    jax.config (which works even while a wedged axon tunnel hangs every
    devices() call — observed r3): the CPU validation path for bench's
    own code.

    The probe window is deliberately short (r3's ~15-min backoff wait
    burned the driver window to no benefit on a wedged tunnel): two
    probes, ~3 min worst case, then give up cleanly."""
    if os.environ.get("TDT_BENCH_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()
    for attempt in range(retries):
        if _probe_backend_subprocess(probe_timeout_s):
            import jax
            return jax.devices()
        if attempt < retries - 1:
            time.sleep(backoff_s)
    raise RuntimeError(
        f"backend never initialized within {retries} probe attempts")


def _chain_fold(out, m: int, k: int):
    """The SHARED chain transform: map a matmul output back to the (m, k)
    bf16 carry. Byte-identical across ag_gemm/gemm_rs/gemm_ar so their
    baselines stay comparable (r3 weak-2: asymmetric folds were the
    prime suspect for the 3.5x baseline split)."""
    import jax.numpy as jnp
    r, c = out.shape
    if r >= m and c >= k:
        full = out[:m, :k]
    else:
        reps0, reps1 = -(-m // r), -(-k // c)
        full = jnp.tile(out, (reps0, reps1))[:m, :k]
    return (full.astype(jnp.float32) * 1e-3).astype(jnp.bfloat16)


def _profile_measured_overlap(extras, part, op, eager_fn):
    """Measured-tier overlap for one fused-family part (docs/perf.md
    "Overlap accounting"): capture ONE eager fused dispatch under
    ``jax.profiler`` (the router's ``device.<op>.fused`` annotation
    then brackets real execution, not trace time), parse the capture
    back (``obs.devprof``) and publish the interval-measured numbers
    in extras. No comm events in the window (world=1 / CPU) keeps the
    explicit ``<part>_overlap_requires_chip`` marker instead of a
    fiction; ``tools/bench_ops.py --regress`` checks this contract's
    wellformedness either way."""
    try:
        import jax
        from triton_dist_tpu.obs import devprof
        from triton_dist_tpu.tools.profiler import group_profile
        with group_profile(f"bench_{part}", devprof.devprof_dir()) as cap:
            jax.block_until_ready(eager_fn())
        summary = devprof.parse_capture(cap.path)
        devprof.publish(summary)
        extras[f"{part}_profile_dir"] = cap.path
        m = summary.get("ops", {}).get(op)
        if m is None:
            # The fused call never ran under its device.<op> label —
            # the annotation-coverage pass guards the router wrapper,
            # so this means the part's call routed off the fused
            # branch entirely; record it rather than guessing.
            extras[f"{part}_profile_unattributed"] = True
            return
        extras[f"{part}_device_compute_ms"] = round(m["compute_ms"], 4)
        extras[f"{part}_device_comm_ms"] = round(m["comm_ms"], 4)
        if m["overlap_pct"] is not None:
            extras[f"{part}_overlap_pct_measured"] = m["overlap_pct"]
            extras[f"{part}_exposed_comm_ms_measured"] = \
                m["exposed_comm_ms"]
        else:
            extras[f"{part}_overlap_requires_chip"] = True
    except Exception as e:  # noqa: BLE001 — measurement color, never the bench
        extras[f"{part}_profile_error"] = _err(e)


def _bench_ag_gemm(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_ag_gemm_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))

    def make_step(impl):
        def f(a, bb):
            return _chain_fold(ag_gemm(a, bb, ctx, impl=impl), m, k)
        return _args_step(f, b)

    flops = 2.0 * m * k * nn  # per-chip share = flops / n
    t_pallas = perf_func_chained(make_step("pallas"), a0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), a0, (8, 24))

    # Autotuned config (eager sweep caches by shape; VERDICT r1 item 5).
    import dataclasses
    from triton_dist_tpu.ops import allgather_gemm as agm
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = agm.ag_gemm(a0, b, tctx, impl="pallas")   # eager → sweep
        tuned_step = _args_step(
            lambda x, bb: _chain_fold(
                agm.ag_gemm(x, bb, tctx, impl="pallas"), m, k), b)
        t_tuned = perf_func_chained(tuned_step, a0, (8, 24))
        key_t = next(iter(k2 for k2 in agm._TUNED
                          if k2[:2] == (m, k)), None)
        extras["ag_gemm_tuned_ms"] = round(t_tuned, 4)
        extras["ag_gemm_tuned_cfg"] = agm._TUNED.get(key_t)
        t_pallas = min(t_pallas, t_tuned)
    except Exception as e:  # noqa: BLE001
        extras["ag_gemm_tune_error"] = _err(e)

    tflops = flops / max(n, 1) / (t_pallas * 1e-3) / 1e12
    extras["ag_gemm_flops"] = flops
    extras["ag_gemm_pallas_ms"] = round(t_pallas, 4)
    extras["ag_gemm_xla_ms"] = round(t_xla, 4)
    extras["ag_gemm_tflops"] = round(tflops, 2)
    extras["ag_gemm_vs_xla"] = round(t_xla / t_pallas, 4)
    _profile_measured_overlap(
        extras, "ag_gemm", "ag_gemm",
        lambda: ag_gemm(a0, b, ctx, impl="pallas"))
    return tflops, t_xla / t_pallas


def _bench_gemm_rs(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_gemm_rs_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    # gemm_rs maps (M, K) -> (M/w, N); the shared fold tiles back up.
    def make_step(impl, c=None):
        ctx2 = ctx if c is None else c

        def f(a, bb):
            return _chain_fold(gemm_rs(a, bb, ctx2, impl=impl), m, k)
        return _args_step(f, b)

    t_ms = {}
    for impl in ("pallas", "xla"):
        t_ms[impl] = perf_func_chained(make_step(impl), a0, (8, 24))

    import dataclasses
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = grs.gemm_rs(a0, b, tctx, impl="pallas")   # eager → sweep
        ms_t = perf_func_chained(make_step("pallas", tctx), a0, (8, 24))
        extras["gemm_rs_tuned_ms"] = round(ms_t, 4)
        extras["gemm_rs_tuned_cfg"] = next(
            (v for kk, v in grs._TUNED.items() if kk[0] == m), None)
        t_ms["pallas"] = min(t_ms["pallas"], ms_t)
    except Exception as e:  # noqa: BLE001
        extras["gemm_rs_tune_error"] = _err(e)
    flops = 2.0 * m * k * nn
    tflops = flops / max(n, 1) / (t_ms["pallas"] * 1e-3) / 1e12
    extras["gemm_rs_flops"] = flops
    extras["gemm_rs_pallas_ms"] = round(t_ms["pallas"], 4)
    extras["gemm_rs_xla_ms"] = round(t_ms["xla"], 4)
    extras["gemm_rs_tflops"] = round(tflops, 2)
    extras["gemm_rs_vs_xla"] = round(t_ms["xla"] / t_ms["pallas"], 4)
    _profile_measured_overlap(
        extras, "gemm_rs", "gemm_rs",
        lambda: gemm_rs(a0, b, ctx, impl="pallas"))
    return tflops, t_ms["xla"] / t_ms["pallas"]


def _bench_gemm_ar(mesh, n, on_tpu, extras):
    """Decode-path GEMM-AllReduce at production width (VERDICT r2 next 5:
    (128, 4096) x (4096, 4096) must run via the hbm path, not VMEM
    residency)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_ar)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (128, 4096, 4096) if on_tpu else (16, 128, 128)
    ctx = create_gemm_rs_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(impl):
        def f(a, bb):
            return _chain_fold(gemm_ar(a, bb, ctx, impl=impl), m, k)
        return _args_step(f, b)

    t_pallas = perf_func_chained(make_step("pallas"), a0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), a0, (8, 24))
    extras["gemm_ar_pallas_ms"] = round(t_pallas, 4)
    extras["gemm_ar_xla_ms"] = round(t_xla, 4)
    extras["gemm_ar_vs_xla"] = round(t_xla / t_pallas, 4)
    _profile_measured_overlap(
        extras, "gemm_ar", "gemm_ar",
        lambda: gemm_ar(a0, b, ctx, impl="pallas"))
    return t_pallas, t_xla / t_pallas


def _bench_flash_decode(mesh, n, on_tpu, extras):
    """Distributed split-KV GQA decode latency at a serving shape
    (VERDICT r2 next 6; reference scaling claim README.md:203-205)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.flash_decode import (
        create_flash_decode_context, gqa_fwd_batch_decode)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        b, hq, hkv, d, t = 8, 32, 8, 128, 8192
    else:
        b, hq, hkv, d, t = 2, 8, 2, 64, 256
    ctx = create_flash_decode_context(
        mesh, "tp", interpret=None if not on_tpu else False,
        variant="tiled", t_blk=512)
    q0 = jax.random.normal(jax.random.PRNGKey(0), (b, hq, d),
                           jnp.float32).astype(jnp.bfloat16)
    kc = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, d),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    vc = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, d),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    kv_len = jnp.int32(t - 7)

    def make_step(impl, c=None):
        def f(q, kcache, vcache, c=ctx if c is None else c):
            out = gqa_fwd_batch_decode(q, kcache, vcache, kv_len, c,
                                       impl=impl)
            return (out.astype(jnp.float32) * 0.5 + 0.5
                    ).astype(jnp.bfloat16)
        return _args_step(f, kc, vc)

    t_pallas = perf_func_chained(make_step("pallas"), q0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), q0, (8, 24))
    if on_tpu:
        # t_blk sweep (failure-isolated like the GEMM sweeps): the split
        # size trades VMEM residency against combine overhead.
        best = (t_pallas, 512)
        for t_blk in (256, 1024, 2048):
            try:
                ctx2 = create_flash_decode_context(
                    mesh, "tp", interpret=False, variant="tiled",
                    t_blk=t_blk)
                ms = perf_func_chained(make_step("pallas", ctx2),
                                      q0, (8, 24))
                if ms < best[0]:
                    best = (ms, t_blk)
            except Exception as e:  # noqa: BLE001 — per-config isolation
                extras[f"flash_decode_tblk{t_blk}_error"] = _err(e)
        extras["flash_decode_best_tblk"] = best[1]
        t_pallas = min(t_pallas, best[0])
    extras["flash_decode_pallas_ms"] = round(t_pallas, 4)
    extras["flash_decode_xla_ms"] = round(t_xla, 4)
    extras["flash_decode_vs_xla"] = round(t_xla / t_pallas, 4)
    return t_pallas, t_xla / t_pallas


def _bench_sp_attention(mesh, n, on_tpu, extras):
    """Long-context prefill attention: fused SP kernel vs XLA AG-KV
    golden (reference sp_ag_attention_inter_node.py; at world=1 this is
    the local flash-path comparison)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.sp_attention import (
        create_sp_attention_context, sp_ag_attention)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        b, s, hq, hkv, d = 1, 4096, 16, 8, 128
    else:
        b, s, hq, hkv, d = 1, 256, 8, 4, 32
    ctx = create_sp_attention_context(
        mesh, "tp", causal=True,
        interpret=None if not on_tpu else False)
    sh = NamedSharding(mesh, P(None, "tp"))
    q0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (b, s, hq, d),
                          jnp.float32).astype(jnp.bfloat16), sh)
    k = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, d),
                          jnp.float32).astype(jnp.bfloat16), sh)
    v = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, d),
                          jnp.float32).astype(jnp.bfloat16), sh)

    def make_step(impl):
        def f(q, kk, vv):
            out = sp_ag_attention(q, kk, vv, ctx, impl=impl)
            return (out.astype(jnp.float32) * 0.5 + 0.5
                    ).astype(jnp.bfloat16)
        return _args_step(f, k, v)

    t_fused = perf_func_chained(make_step("pallas"), q0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), q0, (8, 24))
    extras["sp_attn_fused_ms"] = round(t_fused, 4)
    extras["sp_attn_xla_ms"] = round(t_xla, 4)
    extras["sp_attn_vs_xla"] = round(t_xla / t_fused, 4)
    return t_fused, t_xla / t_fused


def _bench_ag_group_gemm(mesh, n, on_tpu, extras):
    """Fused-Pallas vs ppermute-ring AG+grouped-GEMM (VERDICT r2 next 7:
    measure both on the chip, keep whichever wins)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.group_gemm import (
        create_ag_group_gemm_context, ag_group_gemm)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn, n_exp = (2048, 4096, 4096, 8) if on_tpu else (64, 64, 128, 4)
    ctx = create_ag_group_gemm_context(mesh, "tp")
    ctx.interpret = None if not on_tpu else False
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))
    w = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (n_exp, k, nn),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, None, "tp")))
    eid = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(2), (m,), 0, n_exp,
                           jnp.int32),
        NamedSharding(mesh, P("tp")))

    def make_step(impl):
        def f(x, ww):
            c = ag_group_gemm(x, ww, eid, n_exp, ctx, impl=impl)
            return _chain_fold(c, m, k)
        return _args_step(f, w)

    t_fused = perf_func_chained(make_step("fused"), x0, (8, 24))
    t_ring = perf_func_chained(make_step("ring"), x0, (8, 24))
    extras["moe_ag_gg_fused_ms"] = round(t_fused, 4)
    extras["moe_ag_gg_ring_ms"] = round(t_ring, 4)
    extras["moe_ag_gg_winner"] = ("fused" if t_fused <= t_ring
                                  else "ring")

    # MoE-RS: fused single kernel vs ppermute ring (same VERDICT item).
    from triton_dist_tpu.ops.moe_reduce_rs import (
        create_moe_rs_context, moe_reduce_rs)
    topk = 2
    t_tok, inter, hid = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    mctx = create_moe_rs_context(mesh, "tp", num_experts=n_exp, topk=topk)
    mctx.interpret = None if not on_tpu else False
    act0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(3), (t_tok * topk, inter),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    wdn = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(4), (n_exp, inter, hid),
                          jnp.float32).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    eid2 = jax.random.randint(jax.random.PRNGKey(5), (t_tok * topk,), 0,
                              n_exp, jnp.int32)
    wts = jax.nn.softmax(jax.random.normal(
        jax.random.PRNGKey(6), (t_tok, topk), jnp.float32))

    def make_mrs(impl):
        def f(a, wd):
            out = moe_reduce_rs(a, wd, eid2, wts, mctx, impl=impl)
            return _chain_fold(out, t_tok * topk, inter)
        return _args_step(f, wdn)

    t_mf = perf_func_chained(make_mrs("fused"), act0, (8, 24))
    t_mr = perf_func_chained(make_mrs("ring"), act0, (8, 24))
    extras["moe_rs_fused_ms"] = round(t_mf, 4)
    extras["moe_rs_ring_ms"] = round(t_mr, 4)
    extras["moe_rs_winner"] = "fused" if t_mf <= t_mr else "ring"
    return min(t_fused, t_ring), t_ring / t_fused


def _bench_mega_vs_engine(mesh, n, on_tpu, extras):
    """Megakernel (one fused jit program per decode step) vs the plain
    engine decode step, at the r3 toy depth AND at 32 layers x Qwen3-8B
    per-chip width (VERDICT r3 next-6: 'the claim is unproven where it
    matters'; reference mega_triton_kernel.md:30-39)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.mega import MegaQwen3
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.kv_cache import KVCacheManager
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        configs = [
            ("", ModelConfig(hidden_size=2048, intermediate_size=8192,
                             num_hidden_layers=4, num_attention_heads=16,
                             num_key_value_heads=8, head_dim=128,
                             vocab_size=32768, max_position_embeddings=512,
                             dtype=jnp.bfloat16), 8),
            # Qwen3-8B per-chip TP8 slice at reference depth-class:
            # 32 layers, hidden 4096, heads 32/8, kv 8/8, inter 12288/8.
            # Per-chip dims scale back up with the mesh so a real
            # n-chip run keeps 4 heads / 1536 inter PER CHIP (and
            # satisfies heads % world == 0 — review r4b-1).
            ("deep_", ModelConfig(hidden_size=4096,
                                  intermediate_size=1536 * max(n, 1),
                                  num_hidden_layers=32,
                                  num_attention_heads=4 * max(n, 1),
                                  num_key_value_heads=max(n, 1),
                                  head_dim=128,
                                  vocab_size=32768,
                                  max_position_embeddings=512,
                                  dtype=jnp.bfloat16), 1),
        ]
    else:
        configs = [
            ("", ModelConfig(hidden_size=128, intermediate_size=256,
                             num_hidden_layers=2, num_attention_heads=4,
                             num_key_value_heads=2, head_dim=64,
                             vocab_size=256, max_position_embeddings=64,
                             dtype=jnp.bfloat16), 2),
        ]
        if os.environ.get("TDT_BENCH_DEEP_CPU") == "1":
            # Opt-in (compile alone is ~8 min in interpret mode, far
            # over the part deadline): the 32-layer depth-class run
            # behind VERDICT r4 weak-3/next-4. Measured r5 with
            # min-of-5 windowed timing: deep_mega_vs_engine = 1.114 —
            # the r4 "0.956 at depth" was single-window timing noise
            # (docs/perf.md "mega vs engine at depth").
            configs.append(
                ("deep_", ModelConfig(hidden_size=128,
                                      intermediate_size=256,
                                      num_hidden_layers=32,
                                      num_attention_heads=4,
                                      num_key_value_heads=2, head_dim=64,
                                      vocab_size=256,
                                      max_position_embeddings=64,
                                      dtype=jnp.bfloat16), 2))
    t_mega = t_engine = None
    for prefix, cfg, b in configs:
        model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="pallas")
        params = model.init(jax.random.PRNGKey(0))
        kv = KVCacheManager(cfg.num_hidden_layers, b,
                            cfg.max_position_embeddings,
                            cfg.num_key_value_heads, cfg.head_dim,
                            mesh=mesh, axis="tp", dtype=cfg.dtype)
        caches = kv.init()
        # The chain carry must be FLOAT: perturb_input only perturbs
        # floating leaves, and an int token chain would replay identical
        # computations the tunnel dedupes (code-review r3c finding 1).
        x0 = jnp.ones((b, 1), jnp.float32)
        mega = MegaQwen3(model, decode_mode="gemm_ar")

        def make_step(use_mega, model=model, mega=mega, params=params,
                      caches=caches, cfg=cfg):
            def f(x, p, cc):
                token = (jnp.abs(x) * 997).astype(jnp.int32) % cfg.vocab_size
                if use_mega:
                    logits, _ = mega.step(p, token, cc, 4)
                else:
                    logits, _ = model.forward(p, token, cc,
                                              jnp.int32(4), mode="gemm_ar")
                return jnp.mean(logits[:, -1].astype(jnp.float32), axis=-1,
                                keepdims=True)
            return _args_step(f, params, caches)

        t_mega = perf_func_chained(make_step(True), x0, (8, 24))
        t_engine = perf_func_chained(make_step(False), x0, (8, 24))
        extras[prefix + "mega_step_ms"] = round(t_mega, 4)
        extras[prefix + "engine_step_ms"] = round(t_engine, 4)
        extras[prefix + "mega_vs_engine"] = round(t_engine / t_mega, 4)
        # The reference's mega table reports against BOTH torch-eager
        # and torch+CUDA-graph (mega_triton_kernel.md:30-39). The raw
        # model.forward above is the eager analog (per-op dispatch);
        # the jitted step is the graph analog — the strong baseline the
        # production Engine actually runs.
        try:
            import jax as _jax
            f_eng = make_step(False)
            jit_step = _jax.jit(lambda x: f_eng(x))
            t_jit = perf_func_chained(jit_step, x0, (8, 24))
            extras[prefix + "engine_jit_step_ms"] = round(t_jit, 4)
            extras[prefix + "mega_vs_engine_jit"] = round(t_jit / t_mega,
                                                          4)
        except Exception as e:  # noqa: BLE001
            extras[prefix + "engine_jit_error"] = _err(e)

        if prefix == "deep_" or not on_tpu:
            # Peak temp memory of the fused step, for the record. The
            # r4 topo-vs-heft comparison is gone: emission order is
            # provably inert under XLA (scheduler demoted to perf
            # model, docs/architecture.md "Mega scheduler";
            # tests/test_mega.py::test_heft_emission_inert_under_xla
            # pins it), so re-timing a second emission measured noise.
            try:
                token0 = jnp.zeros((b, 1), jnp.int32)
                flat = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        jnp.shape(a), jnp.result_type(a)),
                    mega.flat_args(params, token0, caches, 4))
                ma = mega._step.lower(*flat).compile().memory_analysis()
                if ma is not None:
                    extras[f"{prefix}mega_temp_bytes"] = int(
                        getattr(ma, "temp_size_in_bytes", 0))
            except Exception as e:  # noqa: BLE001
                extras[prefix + "mega_memory_error"] = _err(e)

        if prefix == "":
            # Continuous-batching hot path: the stream decode step runs
            # every row at its OWN cache position (per-row scatter
            # writes + masks/rope — Engine.serve_stream). Its cost vs
            # the uniform-offset step prices the scheduling flexibility.
            offsets0 = jnp.full((b,), 4, jnp.int32)

            def stream_step(x, p, cc, model=model, cfg=cfg,
                            offsets0=offsets0):
                token = (jnp.abs(x) * 997).astype(jnp.int32) % cfg.vocab_size
                logits, _ = model.forward(p, token, cc,
                                          offsets0 + token[:, 0] % 2,
                                          mode="gemm_ar")
                return jnp.mean(logits[:, -1].astype(jnp.float32), axis=-1,
                                keepdims=True)

            t_stream = perf_func_chained(
                _args_step(stream_step, params, caches), x0, (8, 24))
            extras["stream_step_ms"] = round(t_stream, 4)
            extras["stream_vs_engine_step"] = round(t_engine / t_stream, 4)
    return t_mega, t_engine / t_mega


def _scrape_metrics(host, port):
    from triton_dist_tpu.serving.client import ChatClient
    c = ChatClient(host, port)
    try:
        return c.request({"cmd": "metrics"})["metrics"]
    finally:
        c.close()


def _sample_waterfall(host, port):
    """Newest request's attribution waterfall (obs.attrib via
    {"cmd": "request_stats"}), or None — best-effort bench color."""
    from triton_dist_tpu.serving.client import ChatClient
    try:
        c = ChatClient(host, port)
        try:
            reqs = c.request({"cmd": "request_stats",
                              "last": 1}).get("requests") or []
            return reqs[0] if reqs else None
        finally:
            c.close()
    except Exception:  # noqa: BLE001 — telemetry color, never the bench
        return None


def _hist_delta(before, after, name):
    """The timed window's own histogram: warmup requests share the
    process-global registry, and their cold-compile TTFTs would
    otherwise put jit time into the reported p99."""
    a = (before or {}).get("histograms", {}).get(name)
    b = (after or {}).get("histograms", {}).get(name)
    if not b:
        return None
    if not a:
        return b
    return {"buckets": b["buckets"],
            "counts": [y - x for x, y in zip(a["counts"],
                                             b["counts"])],
            "count": b["count"] - a["count"],
            "sum": b["sum"] - a["sum"],
            # The window's extrema are unknowable from cumulative
            # snapshots (the lifetime max is the warmup's compile
            # time — exactly what this delta excludes); with max=None
            # a +Inf-tail quantile clips to the top finite bucket
            # edge (obs.histogram_quantile overflow handling).
            "min": None, "max": None}


def _served_workload_run(srv, reqs, warm_reqs=None):
    """The shared serving-part harness (_bench_serving scheduler leg /
    _bench_serving_mega / _bench_serving_spec): warm every compile the
    timed window touches, reset the rolling SLO windows so the
    windowed percentiles price the timed run (not the warmup's cold
    compiles), run the timed fanout, and scrape metrics before/after
    for histogram deltas. ``warm_reqs`` overrides the default 2-token
    warmup — the spec part warms with the FULL workload because the
    per-k-bucket verify programs only compile once drafting engages
    (a 2-token budget clamps every draft to zero).
    Returns (tokens_per_s, errors, warm_snapshot, end_snapshot)."""
    from triton_dist_tpu.serving.client import fanout
    fanout(srv.host, srv.port,
           warm_reqs if warm_reqs is not None
           else [dict(r, gen_len=2) for r in reqs])
    if srv.scheduler is not None and srv.scheduler.slo is not None:
        srv.scheduler.slo.reset_windows()
    warm = _scrape_metrics(srv.host, srv.port)
    t0 = time.perf_counter()
    outs = fanout(srv.host, srv.port, reqs)
    dt = time.perf_counter() - t0
    toks = sum(len(o["tokens"][0]) for o in outs if "tokens" in o)
    errors = [o for o in outs if "tokens" not in o]
    snap = _scrape_metrics(srv.host, srv.port)
    return (toks / dt if dt > 0 else 0.0), errors, warm, snap


def _bench_serving(mesh, n, on_tpu, extras):
    """Serving throughput under concurrency (ISSUE 5): N concurrent
    clients with mixed prompt/gen lengths against (a) the
    continuous-batching scheduler and (b) the scheduler=False
    serialized-lock baseline — same model, same params, same workload.

    Both paths run the identical xla-impl model, so kernel quality
    cancels out and ``serving_sched_vs_serial`` prices SCHEDULING
    alone: how much of the per-step cost the shared batch amortizes
    across connections. That makes the ratio valid on the CPU tier
    (the acceptance gate: >= 2x with 8 clients), unlike the *_vs_xla
    kernel ratios which price the interpreter there. TTFT percentiles
    come from the scheduler server's ``serving.ttft_ms`` histogram."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.obs import histogram_quantile
    from triton_dist_tpu.serving import ModelServer
    from triton_dist_tpu.serving.client import ChatClient, fanout

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen_short, gen_long = 16, 96
    else:
        cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=64, max_position_embeddings=256,
                          dtype=jnp.float32)
        gen_short, gen_long = 4, 24
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    clients, batch = 8, 4
    # Prompt lengths stay inside ONE power-of-two admission bucket (8)
    # so both paths pay one prefill compile; gen lengths mix short and
    # long so the scheduler's no-head-of-line-blocking actually shows.
    prompt_lens = [3, 5, 8, 4, 6, 7, 5, 3]
    gens = [gen_long, gen_short, gen_long, gen_short] * 2
    reqs = [{"prompt_ids": [[(7 * i + j) % (cfg.vocab_size - 1) + 1
                             for j in range(pl)]],
             "gen_len": g}
            for i, (pl, g) in enumerate(zip(prompt_lens, gens))]

    hist_delta = _hist_delta

    def run(use_scheduler):
        # Serialized baseline decodes one request at a time → its
        # natural engine is batch-1; the scheduler's is the shared
        # multi-row window. Both see the identical request stream.
        eng = Engine(model, batch=batch if use_scheduler else 1,
                     max_seq=cfg.max_position_embeddings,
                     prefill_mode="xla_ar", decode_mode="gemm_ar")
        srv = ModelServer(eng, params, port=0,
                          scheduler=use_scheduler).start()
        try:
            if use_scheduler:
                # Shared harness: warmup (every compile out of the
                # timed window), rolling-window reset, timed fanout,
                # before/after scrapes. The metrics scrape forces a
                # fresh SLO evaluation, so the serving.rolling.*
                # gauges below are current as of the window's end.
                tps, errors, warm, snap = _served_workload_run(srv,
                                                               reqs)
                return (tps, errors, warm, snap,
                        _sample_waterfall(srv.host, srv.port))
            # Serialized leg: same warmup (the per-prompt-shape eager
            # prefills must not be timed — a cold compile would hand
            # the scheduler a compile-amortization win on top of the
            # scheduling win this probe prices), no scrapes (no
            # scheduler histograms to delta).
            fanout(srv.host, srv.port,
                   [dict(r, gen_len=2) for r in reqs])
            t0 = time.perf_counter()
            outs = fanout(srv.host, srv.port, reqs)
            dt = time.perf_counter() - t0
            toks = sum(len(o["tokens"][0]) for o in outs
                       if "tokens" in o)
            errors = [o for o in outs if "tokens" not in o]
            return (toks / dt if dt > 0 else 0.0, errors, None, None,
                    None)
        finally:
            srv.stop()

    tps_serial, err_s, _, _, _ = run(False)
    tps_sched, err_c, warm, snap, waterfall = run(True)
    if waterfall:
        # One sampled request's attribution waterfall rides inside
        # extras.telemetry (where TTFT went: queue vs prefill vs
        # decode) — tools/report.py renders it.
        extras["serving_waterfall"] = waterfall
    extras["serving_clients"] = clients
    extras["serving_batch_rows"] = batch
    extras["serving_tokens_per_s"] = round(tps_sched, 2)
    extras["serving_serialized_tokens_per_s"] = round(tps_serial, 2)
    if tps_serial > 0:
        extras["serving_sched_vs_serial"] = round(tps_sched / tps_serial,
                                                  4)
    if err_s or err_c:
        extras["serving_errors"] = [str(e)[:120]
                                    for e in (err_s + err_c)[:4]]
    ttft = hist_delta(warm, snap, "serving.ttft_ms")
    if ttft:
        p50 = histogram_quantile(ttft, 0.50)
        p99 = histogram_quantile(ttft, 0.99)
        extras["serving_ttft_p50_ms"] = round(p50, 3) if p50 else None
        extras["serving_ttft_p99_ms"] = round(p99, 3) if p99 else None
    qw = hist_delta(warm, snap, "serving.queue_wait_ms")
    if qw:
        p50 = histogram_quantile(qw, 0.50)
        extras["serving_queue_wait_p50_ms"] = (round(p50, 3) if p50
                                               else None)
    # Rolling-WINDOW percentiles (obs.slo): the windows were reset
    # after warmup and the timed run fits inside one TDT_SLO_WINDOW_S,
    # so these are the timed run's own numbers — no warmup compiles,
    # no process-lifetime dilution. The regress gate pins these keys
    # (tools/bench_ops.py SERVING_ROLLING_KEYS) — unless the operator
    # disabled the SLO engine, which the gate must see as an explicit
    # opt-out, not a missing-metric failure.
    from triton_dist_tpu.obs import slo as _slo
    if not _slo.enabled():
        extras["serving_rolling_disabled"] = True
    else:
        for m in ("ttft", "tpot"):
            for tag in ("p50", "p99"):
                v = (snap or {}).get("gauges", {}).get(
                    f"serving.rolling.{m}_{tag}_ms")
                extras[f"serving_rolling_{m}_{tag}_ms"] = (
                    round(float(v), 3) if v is not None else None)
    return tps_sched, extras.get("serving_sched_vs_serial")


def _bench_serving_mega(mesh, n, on_tpu, extras):
    """Mega-in-scheduler vs plain-in-scheduler (ISSUE 11): the same
    model, same params, same concurrent request stream through the
    same continuous-batching ``StreamSession`` — only the decode path
    differs (``Engine(decode_path="mega")`` vs ``"plain"``). Greedy
    outputs are bit-identical (tests/test_scheduler.py), so
    ``serving_mega_vs_plain`` prices the one-program task-graph step
    against the plain jitted step INSIDE the shared batch — the
    composition ROADMAP item 1 asks for. On the CPU tier the ratio
    mostly prices dispatch parity (floor 0.5, BASELINE.json — a
    harness/wellformedness gate, not a perf claim); the chip number is
    what the next hardware window reads against the 1.49x
    uniform-batch measurement (docs/perf.md)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.obs import histogram_quantile
    from triton_dist_tpu.serving import ModelServer

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen_short, gen_long = 16, 96
    else:
        cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=64, max_position_embeddings=256,
                          dtype=jnp.float32)
        gen_short, gen_long = 4, 24
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    batch = 4
    # Mixed prompt/gen lengths inside one admission bucket (8): ragged
    # per-row offsets + mid-decode admission/retirement are exactly the
    # batch shapes the vectorized mega step must not lose on.
    prompt_lens = [3, 5, 8, 4, 6, 7, 5, 3]
    gens = [gen_long, gen_short, gen_long, gen_short] * 2
    reqs = [{"prompt_ids": [[(7 * i + j) % (cfg.vocab_size - 1) + 1
                             for j in range(pl)]],
             "gen_len": g}
            for i, (pl, g) in enumerate(zip(prompt_lens, gens))]

    def run(path):
        eng = Engine(model, batch=batch,
                     max_seq=cfg.max_position_embeddings,
                     prefill_mode="xla_ar", decode_mode="gemm_ar",
                     decode_path=path)
        srv = ModelServer(eng, params, port=0).start()
        try:
            # Shared harness (warmup incl. this path's decode-step
            # compile, rolling-window reset, timed fanout, scrapes).
            return _served_workload_run(srv, reqs)
        finally:
            srv.stop()

    from triton_dist_tpu.obs import slo as _slo
    results = {}
    for path in ("plain", "mega"):
        tps, errors, warm, snap = run(path)
        results[path] = tps
        tag = "serving_mega" if path == "mega" else "serving_mega_plain"
        extras[f"{tag}_tokens_per_s"] = round(tps, 2)
        if errors:
            extras[f"{tag}_errors"] = [str(e)[:120]
                                       for e in errors[:4]]
        ttft = _hist_delta(warm, snap, "serving.ttft_ms")
        if ttft:
            for q, qtag in ((0.50, "p50"), (0.99, "p99")):
                v = histogram_quantile(ttft, q)
                extras[f"{tag}_ttft_{qtag}_ms"] = (round(v, 3) if v
                                                   else None)
        # TPOT from the freshly-reset rolling windows (the timed run's
        # own percentiles, same contract — and same TDT_SLO=0 opt-out
        # — as the serving part).
        if not _slo.enabled():
            extras["serving_rolling_disabled"] = True
        else:
            for qtag in ("p50", "p99"):
                v = (snap or {}).get("gauges", {}).get(
                    f"serving.rolling.tpot_{qtag}_ms")
                extras[f"{tag}_tpot_{qtag}_ms"] = (
                    round(float(v), 3) if v is not None else None)
    if results["plain"] > 0:
        extras["serving_mega_vs_plain"] = round(
            results["mega"] / results["plain"], 4)
    return results["mega"], extras.get("serving_mega_vs_plain")


def _bench_serving_spec(mesh, n, on_tpu, extras):
    """Speculative decoding on vs off through the SAME scheduler
    (ISSUE 13): identical model, params, and concurrent request stream
    — only ``Engine(spec=SpecConfig(drafter="ngram"))`` differs.
    Greedy outputs are bit-identical (tests/test_scheduler.py), so
    ``serving_spec_vs_plain`` prices TOKENS PER STEP: each widened
    verify step costs about one decode step but emits 1..k+1 tokens.
    The workload is repetition-friendly (requests share a templated,
    self-repeating prompt family) because that is the regime the
    model-free n-gram drafter targets — the ratio is CPU-valid like
    the other serving parts (scheduling/dispatch parity, kernels
    cancel) and floor-gated at the ISSUE 13 acceptance bar (> 1.0,
    BASELINE.json cpu tier)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.models.spec import SpecConfig
    from triton_dist_tpu.obs import histogram_quantile
    from triton_dist_tpu.serving import ModelServer

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen = 96
    else:
        # Smaller than the sibling serving parts ON PURPOSE: a tighter
        # state space settles into repetitive greedy tails sooner (the
        # drafter's win regime), and a dispatch-dominated step prices
        # the verify window against the plain step most directly.
        cfg = ModelConfig(hidden_size=16, intermediate_size=32,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=32, max_position_embeddings=256,
                          dtype=jnp.float32)
        gen = 160
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(3))
    batch = 4
    # Repetition-friendly workload: long generations from a fixed-seed
    # tiny model settle into short greedy cycles, which is exactly the
    # regime prompt-lookup drafting targets (templated text/code).
    # Every client sends the same early-cycling prompt (probed for
    # PRNGKey(3)), so the whole batch sits in the drafter's win regime
    # — the spec-off leg runs the identical stream, so the ratio still
    # prices tokens per step, not workload luck. k=8 commits up to 9
    # tokens per verify step on a period-<=8 cycle.
    prompt = [15, 16, 17, 18, 19, 20, 21, 22]
    reqs = [{"prompt_ids": [list(prompt)], "gen_len": gen}
            for _ in range(8)]

    def run(spec):
        eng = Engine(model, batch=batch,
                     max_seq=cfg.max_position_embeddings,
                     prefill_mode="xla_ar", decode_mode="gemm_ar",
                     spec=spec)
        srv = ModelServer(eng, params, port=0).start()
        try:
            # Shared harness; the SPEC leg warms with the full
            # workload so every per-k-bucket verify program compiles
            # before the timed window (a 2-token warmup budget never
            # drafts) — the plain leg has no such programs and keeps
            # the cheap 2-token default.
            return _served_workload_run(
                srv, reqs, warm_reqs=reqs if spec is not None else None)
        finally:
            srv.stop()

    from triton_dist_tpu.obs import slo as _slo
    results = {}
    for tag, spec in (("plain", None),
                      ("spec", SpecConfig(k=8, drafter="ngram"))):
        tps, errors, warm, snap = run(spec)
        results[tag] = tps
        key = "serving_spec" if tag == "spec" else "serving_spec_plain"
        extras[f"{key}_tokens_per_s"] = round(tps, 2)
        if errors:
            extras[f"{key}_errors"] = [str(e)[:120]
                                       for e in errors[:4]]
        ttft = _hist_delta(warm, snap, "serving.ttft_ms")
        if ttft:
            v = histogram_quantile(ttft, 0.50)
            extras[f"{key}_ttft_p50_ms"] = round(v, 3) if v else None
        if tag == "spec":
            g = (snap or {}).get("gauges", {})
            for gk, ek in (("serving.spec_accept_rate",
                            "serving_spec_accept_rate"),
                           ("serving.spec_tokens_per_step",
                            "serving_spec_tokens_per_step")):
                v = g.get(gk)
                extras[ek] = round(float(v), 4) if v is not None \
                    else None
            if not _slo.enabled():
                extras["serving_rolling_disabled"] = True
            else:
                for qtag in ("p50", "p99"):
                    v = g.get(f"serving.rolling.tpot_{qtag}_ms")
                    extras[f"{key}_tpot_{qtag}_ms"] = (
                        round(float(v), 3) if v is not None else None)
    if results["plain"] > 0:
        extras["serving_spec_vs_plain"] = round(
            results["spec"] / results["plain"], 4)
    return results["spec"], extras.get("serving_spec_vs_plain")


def _bench_serving_history(mesh, n, on_tpu, extras):
    """The history plane's overhead, priced (ISSUE 16): the SAME
    model, scheduler, and concurrent request stream served twice —
    sampler off (the default; its zero-overhead-when-unused contract)
    vs on at an aggressive 20 Hz tick (``TDT_HISTORY=1``,
    ``TDT_HISTORY_TICK_S=0.05`` — 20x the default cadence, so the
    measured ratio BOUNDS the deployed cost). The on-leg's throughput
    ratio ``serving_history_on_vs_off`` is floor-gated in
    BASELINE.json (cpu tier): a background thread doing lock-free
    registry peeks must not meaningfully tax the pump. The on-leg's
    ``{"cmd": "history"}`` snapshot is embedded for report.py's
    "history" section, and its tick/series counts are the
    well-formedness evidence ``bench_ops --regress`` checks."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.serving import ModelServer
    from triton_dist_tpu.serving.client import ChatClient

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen = 48
    else:
        cfg = ModelConfig(hidden_size=16, intermediate_size=32,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=32, max_position_embeddings=128,
                          dtype=jnp.float32)
        gen = 32
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(4))
    reqs = [{"prompt_ids": [[5, 6, 7, (11 + i) % cfg.vocab_size]],
             "gen_len": gen} for i in range(8)]

    _HIST_ENV = ("TDT_HISTORY", "TDT_HISTORY_TICK_S")

    def run(history_on):
        # The scheduler reads TDT_HISTORY* at CONSTRUCTION
        # (HistorySampler.from_env), so the env toggle must bracket
        # the ModelServer build — and must be restored even when the
        # leg dies, or the off-leg would silently sample.
        saved = {k: os.environ.get(k) for k in _HIST_ENV}
        if history_on:
            os.environ["TDT_HISTORY"] = "1"
            os.environ["TDT_HISTORY_TICK_S"] = "0.05"
        else:
            for k in _HIST_ENV:
                os.environ.pop(k, None)
        try:
            eng = Engine(model, batch=4,
                         max_seq=cfg.max_position_embeddings,
                         prefill_mode="xla_ar", decode_mode="gemm_ar")
            srv = ModelServer(eng, params, port=0).start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        try:
            tps, errors, warm, snap = _served_workload_run(srv, reqs)
            hist = None
            if history_on:
                c = ChatClient(srv.host, srv.port, timeout=30.0)
                try:
                    hist = c.request(
                        {"cmd": "history", "max_points": 64})["history"]
                finally:
                    c.close()
            return tps, errors, snap, hist
        finally:
            srv.stop()

    results = {}
    for tag, on in (("off", False), ("on", True)):
        tps, errors, snap, hist = run(on)
        results[tag] = tps
        key = ("serving_history" if on
               else "serving_history_off")
        extras[f"{key}_tokens_per_s"] = round(tps, 2)
        if errors:
            extras[f"{key}_errors"] = [str(e)[:120]
                                       for e in errors[:4]]
        if on:
            c = (snap or {}).get("counters", {})
            extras["serving_history_ticks"] = int(
                c.get("history.ticks", 0))
            extras["serving_history_warnings"] = int(
                c.get("history.warnings", 0))
            extras["serving_history_series"] = (
                len((hist or {}).get("series") or {}))
            if hist and hist.get("series"):
                # Rides under extras.telemetry.history only (report.py
                # "history" section) — extras itself stays a flat
                # scalar map for the regress gate.
                extras["history_snapshot"] = hist
    if results["off"] > 0:
        extras["serving_history_on_vs_off"] = round(
            results["on"] / results["off"], 4)
    return results["on"], extras.get("serving_history_on_vs_off")


def _bench_serving_fleet(mesh, n, on_tpu, extras):
    """The first measured multi-replica number (ISSUE 14): TWO
    in-process ``ModelServer`` replicas — same model, same params,
    same per-replica engine config, each with its OWN metrics
    registry (``registry="private"``) — behind a client-side
    round-robin fanout, vs ONE replica of the identical config on the
    same request stream. ``serving_fleet_vs_single`` prices the
    scale-out: two pumps decoding two shared batches against one.

    The fleet-merged percentiles come from BUCKET-MERGED per-replica
    histogram deltas (``obs.fleet.merge_fleet_snapshots`` over the
    timed window's ``serving.ttft_ms`` / ``serving.tpot_ms`` deltas
    — summed buckets through ``histogram_quantile``, never averaged
    per-replica percentiles), and a post-window ``FleetView`` poll
    records per-replica liveness: ``bench_ops --regress``'s
    ``check_fleet_wellformed`` fails the run if either replica was
    not live (a half-dead fleet's tokens/s is a single-replica
    number). CPU-valid like the sibling serving parts (identical xla
    model on both legs) but GIL-shared on a 1-core container, so the
    BASELINE floor is deliberately generous."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.obs import merge_snapshots
    from triton_dist_tpu.obs.fleet import (
        PERCENTILE_HISTOGRAMS, FleetView, merged_percentiles)
    from triton_dist_tpu.serving import ModelServer
    from triton_dist_tpu.serving.client import fanout

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen_short, gen_long = 16, 96
    else:
        cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=64, max_position_embeddings=256,
                          dtype=jnp.float32)
        gen_short, gen_long = 4, 24
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    clients, batch = 8, 2       # per-replica rows; fleet = 2 replicas
    prompt_lens = [3, 5, 8, 4, 6, 7, 5, 3]
    gens = [gen_long, gen_short, gen_long, gen_short] * 2
    reqs = [{"prompt_ids": [[(7 * i + j) % (cfg.vocab_size - 1) + 1
                             for j in range(pl)]],
             "gen_len": g}
            for i, (pl, g) in enumerate(zip(prompt_lens, gens))]

    def scrape(srv):
        return _scrape_metrics(srv.host, srv.port)

    def run(n_replicas):
        engines = [Engine(model, batch=batch,
                          max_seq=cfg.max_position_embeddings,
                          prefill_mode="xla_ar", decode_mode="gemm_ar")
                   for _ in range(n_replicas)]
        srvs = [ModelServer(eng, params, port=0, registry="private",
                            replica_id=f"bench-r{i}").start()
                for i, eng in enumerate(engines)]
        eps = [(s.host, s.port) for s in srvs]
        try:
            # Same harness shape as _served_workload_run, fleet-wide:
            # warm every replica's compiles, reset every replica's
            # rolling windows, then time one round-robin fanout.
            fanout(endpoints=eps,
                   requests=[dict(r, gen_len=2) for r in reqs])
            for s in srvs:
                if s.scheduler is not None and s.scheduler.slo \
                        is not None:
                    s.scheduler.slo.reset_windows()
            warm = {s.replica_id: scrape(s) for s in srvs}
            t0 = time.perf_counter()
            outs = fanout(endpoints=eps, requests=reqs)
            dt = time.perf_counter() - t0
            toks = sum(len(o["tokens"][0]) for o in outs
                       if "tokens" in o)
            errors = [o for o in outs if "tokens" not in o]
            snaps = {s.replica_id: scrape(s) for s in srvs}
            # Liveness during the window, from the fleet view itself.
            view = FleetView(eps)
            rows = view.poll()
            return ((toks / dt if dt > 0 else 0.0), errors, warm,
                    snaps, rows, view.scrape_metrics(evaluate=True))
        finally:
            for s in srvs:
                s.stop()

    tps_single, err_1, _, _, _, _ = run(1)
    tps_fleet, err_2, warm, snaps, rows, merged = run(2)
    extras["serving_fleet_clients"] = clients
    extras["serving_fleet_replica_rows"] = batch
    extras["serving_fleet_tokens_per_s"] = round(tps_fleet, 2)
    extras["serving_fleet_single_tokens_per_s"] = round(tps_single, 2)
    if tps_single > 0:
        extras["serving_fleet_vs_single"] = round(
            tps_fleet / tps_single, 4)
    extras["serving_fleet_replica_ids"] = sorted(snaps)
    extras["serving_fleet_down_replicas"] = sum(
        1 for r in rows if r["status"] != "live")
    # The liveness evidence the gate actually needs: per-replica
    # retired-row DELTAS over the timed window. A replica whose pump
    # died mid-window still answers health/metrics from its handler
    # threads (status "live"), but its delta is zero — and the error
    # counts catch the requests that degraded client-side. Both are
    # gated by check_fleet_wellformed: a half-dead fleet must not
    # publish its tokens/s as a 2-replica number.
    extras["serving_fleet_replica_retired"] = [
        int((snaps[rid].get("counters", {}).get("serving.retired", 0))
            - (warm[rid].get("counters", {}).get("serving.retired", 0)))
        for rid in sorted(snaps)]
    extras["serving_fleet_error_count"] = len(err_2)
    extras["serving_fleet_single_error_count"] = len(err_1)
    if err_1 or err_2:
        extras["serving_fleet_errors"] = [str(e)[:120]
                                          for e in (err_1 + err_2)[:4]]
    # Fleet percentiles of the timed window: per-replica histogram
    # deltas, bucket-merged, interpolated from the SUMMED buckets
    # (the shared fleet-percentile home, obs.fleet.merged_percentiles).
    merged_deltas = {}
    for name, _ in PERCENTILE_HISTOGRAMS:
        deltas = [d for d in
                  (_hist_delta(warm[rid], snaps[rid], name)
                   for rid in snaps) if d]
        if deltas:
            merged_deltas[name] = merge_snapshots(
                [{"histograms": {name: d}}
                 for d in deltas])["histograms"][name]
    for label, p in merged_percentiles(merged_deltas).items():
        for qtag in ("p50", "p99"):
            v = p[qtag]
            extras[f"serving_fleet_{label}_{qtag}_ms"] = (
                round(v, 3) if v is not None else None)
    if merged is not None:
        # The merged snapshot itself rides under extras.telemetry
        # (tools/report.py "fleet" section) — extras stays a flat
        # scalar map for the regress gate, like the waterfalls.
        extras["fleet_snapshot"] = merged
    return tps_fleet, extras.get("serving_fleet_vs_single")


def _bench_serving_router(mesh, n, on_tpu, extras):
    """The fault-tolerant router under measurement AND under fire
    (ISSUE 15): THREE in-process ``ModelServer`` replicas — same
    model/params/config, private registries — first behind client-side
    round-robin (the direct leg), then behind a ``RouterServer``
    (``serving_router_vs_direct`` prices the router hop: placement,
    breaker gate, one extra socket round trip per request), and
    finally the chaos acceptance scenario: a traffic window through
    the router with one replica KILLED mid-window
    (``testing.chaos.kill_replica`` — connections severed, listener
    closed, pump stopped). The headline numbers are the gate's
    (tools/bench_ops.py ``check_router_wellformed``): ZERO
    client-visible failures, >= 1 recorded failover (the response
    carries ``failovers``), and the victim marked ``down`` within the
    configured age. The router's ``replica_down`` flight dump is
    validated and its path published; one failover response's
    trace_id + timing ride under ``extras.telemetry.router_waterfall``
    so the report shows the stitched hop."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.serving import ModelServer, RouterServer
    from triton_dist_tpu.serving.client import ChatClient, fanout
    from triton_dist_tpu.testing import chaos

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=512,
                          dtype=jnp.bfloat16)
        gen_short, gen_long, gen_kill = 16, 96, 128
    else:
        cfg = ModelConfig(hidden_size=32, intermediate_size=64,
                          num_hidden_layers=1, num_attention_heads=4,
                          num_key_value_heads=4, head_dim=8,
                          vocab_size=64, max_position_embeddings=256,
                          dtype=jnp.float32)
        gen_short, gen_long, gen_kill = 4, 24, 48
    model = DenseLLM(cfg, mesh=mesh, axis="tp", impl="xla")
    params = model.init(jax.random.PRNGKey(0))
    clients, batch, replicas = 9, 2, 3
    down_s = 3.0
    prompt_lens = [3, 5, 8, 4, 6, 7, 5, 3, 6]
    gens = [gen_long, gen_short, gen_long] * 3
    reqs = [{"prompt_ids": [[(7 * i + j) % (cfg.vocab_size - 1) + 1
                             for j in range(pl)]],
             "gen_len": g}
            for i, (pl, g) in enumerate(zip(prompt_lens, gens))]

    srvs = [ModelServer(Engine(model, batch=batch,
                               max_seq=cfg.max_position_embeddings,
                               prefill_mode="xla_ar",
                               decode_mode="gemm_ar"),
                        params, port=0, registry="private",
                        replica_id=f"router-r{i}").start()
            for i in range(replicas)]
    eps = [(s.host, s.port) for s in srvs]
    router = RouterServer(
        eps, registry="private", poll_s=0.1, try_timeout_s=30.0,
        deadline_s=120.0,
        fleet_kwargs={"stale_s_": 1.0, "down_s_": down_s}).start()
    rc = ChatClient(router.host, router.port, timeout=180)
    try:
        # Warm every replica's compiles through BOTH paths.
        fanout(endpoints=eps,
               requests=[dict(r, gen_len=2) for r in reqs])
        fanout(router.host, router.port,
               requests=[dict(r, gen_len=2) for r in reqs])

        # Direct leg: client-side round-robin straight at the fleet.
        t0 = time.perf_counter()
        outs_d = fanout(endpoints=eps, requests=reqs)
        dt_d = time.perf_counter() - t0
        toks_d = sum(len(o["tokens"][0]) for o in outs_d
                     if "tokens" in o)
        err_d = [o for o in outs_d if "tokens" not in o]

        # Router leg: same requests through the front door.
        t0 = time.perf_counter()
        outs_r = fanout(router.host, router.port, requests=reqs)
        dt_r = time.perf_counter() - t0
        toks_r = sum(len(o["tokens"][0]) for o in outs_r
                     if "tokens" in o)
        err_r = [o for o in outs_r if "tokens" not in o]

        tps_d = toks_d / dt_d if dt_d > 0 else 0.0
        tps_r = toks_r / dt_r if dt_r > 0 else 0.0
        extras["serving_router_clients"] = clients
        extras["serving_router_replicas"] = replicas
        extras["serving_router_tokens_per_s"] = round(tps_r, 2)
        extras["serving_router_direct_tokens_per_s"] = round(tps_d, 2)
        if tps_d > 0:
            extras["serving_router_vs_direct"] = round(tps_r / tps_d, 4)
        if err_d or err_r:
            extras["serving_router_errors"] = [
                str(e)[:120] for e in (err_d + err_r)[:4]]

        # Kill window: long generations through the router; kill
        # whichever replica holds in-flight dispatches mid-window.
        import threading
        kill_reqs = [dict(r, gen_len=gen_kill) for r in reqs]
        window: dict = {}

        def traffic():
            window["outs"] = fanout(router.host, router.port,
                                    requests=kill_reqs)
        th = threading.Thread(target=traffic, daemon=True)
        th.start()
        victim_idx, deadline = None, time.perf_counter() + 20.0
        while victim_idx is None and time.perf_counter() < deadline:
            rows = rc.request({"cmd": "router_status"}
                              )["router"]["replicas"]
            busy = [i for i, r in enumerate(rows)
                    if r["inflight"] > 0]
            if busy:
                victim_idx = busy[0]
            else:
                time.sleep(0.005)
        if victim_idx is None:
            victim_idx = 0          # kill anyway; the gate will judge
        victim = srvs[victim_idx]
        victim_ep = f"{victim.host}:{victim.port}"
        t_kill = time.perf_counter()
        chaos.kill_replica(victim)

        # Detection latency is timestamped by a CONCURRENT watcher —
        # measuring after th.join() would conflate the remaining
        # traffic window's duration with the router's detection time
        # and trip the gate on any slow container (review finding).
        detect_box: dict = {}

        def watch_down():
            deadline = time.perf_counter() + down_s + 20.0
            while time.perf_counter() < deadline:
                try:
                    rows = rc.request({"cmd": "router_status"}
                                      )["router"]["replicas"]
                except Exception:  # noqa: BLE001 — keep watching
                    time.sleep(0.05)
                    continue
                st = {r["endpoint"]: r["status"] for r in rows}
                if st.get(victim_ep) == "down":
                    detect_box["s"] = time.perf_counter() - t_kill
                    return
                time.sleep(0.05)
        watcher = threading.Thread(target=watch_down, daemon=True)
        watcher.start()
        th.join(timeout=300)
        outs_k = window.get("outs") or []
        err_k = [o for o in outs_k if "tokens" not in o]
        failovers = sum(int(o.get("failovers", 0)) for o in outs_k
                        if isinstance(o, dict))
        extras["serving_router_kill_client_errors"] = len(err_k)
        if err_k:
            extras["serving_router_kill_errors"] = [
                str(e)[:120] for e in err_k[:4]]
        extras["serving_router_failovers"] = failovers
        extras["serving_router_down_s"] = down_s
        watcher.join(timeout=down_s + 25.0)
        if "s" in detect_box:
            extras["serving_router_down_detect_s"] = round(
                detect_box["s"], 3)

        # The postmortem evidence: the router's replica_down flight
        # dump (validated), the router status snapshot, and one
        # failover response's trace-stitched waterfall.
        status = rc.request({"cmd": "router_status"})["router"]
        hop = next((o for o in outs_k if isinstance(o, dict)
                    and o.get("failovers")), None)
        if hop is not None:
            # The trace-ID-stitched hop: this ID filters to the
            # victim's admit, the router's failover instant, and the
            # survivor's retire in the flight dump below.
            status["failover_sample"] = {
                "trace_id": hop.get("trace_id"),
                "failovers": hop.get("failovers"),
                "replica": hop.get("replica"),
                "timing": hop.get("timing"),
            }
        extras["router_snapshot"] = status
        from triton_dist_tpu.obs import trace as _trc
        stats = _trc.stats() if _trc.enabled() else {}
        dump = stats.get("last_flight_record")
        if dump:
            extras["serving_router_flight_record"] = dump
            try:
                from triton_dist_tpu.tools import trace_export
                with open(dump) as f:
                    chrome = json.load(f)
                errors, _w = trace_export.validate(chrome)
                extras["serving_router_flight_valid"] = not errors
            except Exception as e:  # noqa: BLE001 — evidence is extra
                extras["serving_router_flight_valid"] = False
                extras["serving_router_flight_error"] = _err(e)
    finally:
        rc.close()
        router.stop()
        for s in srvs:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — victim already dead
                pass
    return (extras.get("serving_router_tokens_per_s"),
            extras.get("serving_router_vs_direct"))


def _bench_serving_disagg(mesh, n, on_tpu, extras):
    """Disaggregated prefill/decode vs the unified fleet (ISSUE 18):
    ONE prefill + TWO decode paged replicas behind a TIERED
    ``RouterServer`` — single-prompt generates take the
    ``disagg_prefill`` path (prefill admits, streams finished KV
    blocks to the placed decode replica keyed by the prefix cache's
    sha1 chain, decode verifies the chain and admits DECODE-ONLY) —
    against THREE unified replicas behind an untiered router. Same
    model/params/paged-engine config on both legs; the workload's
    prompts share one long preamble so the content-addressed dedup
    has a chain to find (steady-state handoffs ship near-zero
    blocks). ``serving_disagg_vs_unified`` prices the whole
    specialization, handoff latency included (floor-gated generously
    in BASELINE.json's cpu tier — one GIL carries six pumps + two
    routers); the gate (tools/bench_ops.py ``check_disagg_wellformed``)
    also requires >= 1 COMPLETED handoff and a dedup ratio in [0, 1].
    The disagg fleet's private-registry ``disagg.*`` metrics ride
    under ``extras.telemetry`` (report.py "disagg" section) via
    ``disagg_snapshot``."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.obs import histogram_quantile, merge_snapshots
    from triton_dist_tpu.serving import ModelServer, RouterServer
    from triton_dist_tpu.serving.client import fanout

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=1024,
                          dtype=jnp.bfloat16)
        page, preamble_len, tail_len, gen = 16, 512, 8, 8
    else:
        # Prefill-heavy on purpose (same sizing rationale as the
        # prefix part): the handoff moves PREFILL work off the decode
        # replicas, so prefill compute must dominate dispatch overhead
        # for the ratio to price anything real on the CPU tier.
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=16,
                          vocab_size=256, max_position_embeddings=512,
                          dtype=jnp.float32)
        page, preamble_len, tail_len, gen = 16, 192, 4, 4
    devs = np.asarray([d for d in mesh.devices.flat])
    mesh2 = Mesh(devs.reshape(1, -1), ("tp", "sp"))
    max_seq = cfg.max_position_embeddings
    assert max_seq % (len(devs) * page) == 0
    model = DenseLLM(cfg, mesh=mesh2, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    clients, batch = 9, 4
    preamble = [(13 * j) % (cfg.vocab_size - 1) + 1
                for j in range(preamble_len)]
    reqs = [{"prompt_ids": [preamble + [(7 * i + j) % 61 + 1
                                        for j in range(tail_len)]],
             "gen_len": gen}
            for i in range(clients)]

    def run(tiers):
        srvs = [ModelServer(
            Engine(model, batch=batch, max_seq=max_seq,
                   prefill_mode="sp", decode_mode="sp", paged=True,
                   page_size=page, prefix_cache=True),
            params, port=0, registry="private",
            replica_id=f"disagg-{t[0]}{i}", tier=t).start()
            for i, t in enumerate(tiers)]
        router = RouterServer(
            [(s.host, s.port) for s in srvs], registry="private",
            poll_s=0.1, try_timeout_s=60.0, deadline_s=240.0,
            fleet_kwargs={"stale_s_": 2.0, "down_s_": 10.0}).start()
        try:
            # Tier pickup is health-advertised: wait for the poll to
            # see every role before timing (an untiered fleet is all
            # "unified" and passes immediately).
            deadline = time.perf_counter() + 20.0
            want = set(tiers)
            while time.perf_counter() < deadline:
                rows = router.status()["replicas"]
                if {r.get("tier") for r in rows} >= want:
                    break
                time.sleep(0.05)
            # Warmup compiles every bucket the timed window touches
            # through the front door — and, on the tiered leg, runs
            # the first COLD handoffs so the decode replicas' prefix
            # caches hold the preamble chain (the steady state the
            # dedup ratio reports).
            fanout(router.host, router.port, timeout=600,
                   requests=[dict(r, gen_len=2) for r in reqs])
            t0 = time.perf_counter()
            outs = fanout(router.host, router.port, timeout=600,
                          requests=reqs)
            dt = time.perf_counter() - t0
            toks = sum(len(o["tokens"][0]) for o in outs
                       if "tokens" in o)
            errors = [o for o in outs if "tokens" not in o]
            tps = toks / dt if dt > 0 else 0.0
            snaps = [s.registry.snapshot() for s in srvs]
            return tps, errors, snaps, router.status()["counters"]
        finally:
            router.stop()
            for s in srvs:
                s.stop()

    tps_u, err_u, _, _ = run(("unified",) * 3)
    tps_d, err_d, snaps, rctr = run(("prefill", "decode", "decode"))

    extras["serving_disagg_clients"] = clients
    extras["serving_disagg_tokens_per_s"] = round(tps_d, 2)
    extras["serving_disagg_unified_tokens_per_s"] = round(tps_u, 2)
    ratio = round(tps_d / tps_u, 4) if tps_u > 0 else None
    extras["serving_disagg_vs_unified"] = ratio
    if err_u or err_d:
        extras["serving_disagg_errors"] = [
            str(e)[:120] for e in (err_u + err_d)[:4]]

    merged = merge_snapshots(snaps)
    ctr = merged.get("counters", {})
    extras["serving_disagg_handoffs"] = int(ctr.get("disagg.handoffs",
                                                    0))
    extras["serving_disagg_fallbacks"] = int(ctr.get("disagg.fallbacks",
                                                     0))
    extras["serving_disagg_dispatches"] = int(
        rctr.get("router.disagg_dispatches", 0))
    offered = ctr.get("disagg.blocks_offered", 0)
    if offered:
        extras["serving_disagg_dedup_ratio"] = round(
            ctr.get("disagg.blocks_deduped", 0) / offered, 4)
    h = merged.get("histograms", {}).get("disagg.handoff_ms")
    if h:
        for q, tag in ((0.50, "p50"), (0.99, "p99")):
            v = histogram_quantile(h, q)
            extras[f"serving_disagg_handoff_{tag}_ms"] = (
                round(v, 3) if v is not None else None)
    # The disagg fleet's metrics for the report's "disagg" section:
    # ONLY the disagg.* namespace — the six replicas' serving.*
    # counters would masquerade as one server's in the telemetry
    # merge.
    extras["disagg_snapshot"] = {
        "counters": {k: v for k, v in ctr.items()
                     if k.startswith("disagg.")},
        "histograms": {k: v
                       for k, v in merged.get("histograms", {}).items()
                       if k.startswith("disagg.")},
    }
    return (extras.get("serving_disagg_tokens_per_s"), ratio)


def _bench_prefix(mesh, n, on_tpu, extras):
    """Cross-request prefix caching (ISSUE 6): 8 clients sharing one
    long system preamble against the paged block-granular scheduler,
    warm (cache on — the warmup indexes the preamble blocks, so each
    timed request prefills only its few-token suffix) vs cold (cache
    off — every request prefills the full prompt). Both paths run the
    identical xla-impl sp-paged engine, so kernel quality cancels and
    ``serving_prefix_ttft_vs_cold`` prices the prefill tokens SKIPPED —
    valid on the CPU tier, where the acceptance gate is >= 2x warm TTFT
    p50 (BASELINE.json cpu floor, tools/bench_ops.py --regress)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from triton_dist_tpu.models import DenseLLM, Engine, ModelConfig
    from triton_dist_tpu.obs import histogram_quantile
    from triton_dist_tpu.serving import ModelServer

    if on_tpu:
        cfg = ModelConfig(hidden_size=512, intermediate_size=1024,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=64,
                          vocab_size=2048, max_position_embeddings=1024,
                          dtype=jnp.bfloat16)
        page, preamble_len, tail_len, gen = 16, 512, 8, 8
    else:
        # Sized so prefill COMPUTE dominates dispatch overhead on the
        # CPU tier (a 32-wide 1-layer model admits in ~3 ms regardless
        # of prompt length — all dispatch — and the ratio this part
        # prices would drown): ~30 ms cold vs ~7 ms warm admissions.
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=8,
                          num_key_value_heads=8, head_dim=16,
                          vocab_size=256, max_position_embeddings=512,
                          dtype=jnp.float32)
        page, preamble_len, tail_len, gen = 16, 448, 4, 4
    # sp mode needs an sp axis; keep tp trivial so the part runs on any
    # device count (the sp world is what pages shard over).
    devs = np.asarray([d for d in mesh.devices.flat])
    mesh2 = Mesh(devs.reshape(1, -1), ("tp", "sp"))
    max_seq = cfg.max_position_embeddings
    assert max_seq % (len(devs) * page) == 0
    model = DenseLLM(cfg, mesh=mesh2, axis="tp", sp_axis="sp",
                     impl="xla", fwd_mode="sp")
    params = model.init(jax.random.PRNGKey(0))
    clients, batch = 8, 8
    preamble = [(13 * j) % (cfg.vocab_size - 1) + 1
                for j in range(preamble_len)]
    prompts = [preamble + [(7 * i + j) % 61 + 1
                           for j in range(tail_len)]
               for i in range(clients)]

    def run(cache_on):
        eng = Engine(model, batch=batch, max_seq=max_seq,
                     prefill_mode="sp", decode_mode="sp", paged=True,
                     page_size=page, prefix_cache=cache_on)
        srv = ModelServer(eng, params, port=0).start()
        try:
            from triton_dist_tpu.serving.client import ChatClient
            c = ChatClient(srv.host, srv.port, timeout=600)
            # Warmup compiles every program the timed window touches —
            # the cold full-prompt admission bucket, the decode step,
            # and (cache on) the suffix admission bucket; with the
            # cache on it ALSO indexes the preamble blocks, which is
            # exactly the warm-cache condition this part prices.
            c.generate_ids(prompts[:2], gen_len=2)
            warm = _scrape_metrics(srv.host, srv.port)
            # ONE atomic 8-prompt request: all rows admit back-to-back
            # inside a single pump iteration, BEFORE the first shared
            # decode step — so per-row TTFT prices admission prefill
            # alone. (With 8 separate connections the arrivals trickle
            # and each admission queues behind ~O(max_seq) gathered
            # decode steps, which drowns the warm/cold difference.)
            t0 = time.perf_counter()
            out = c.generate_ids(prompts, gen_len=gen)
            dt = time.perf_counter() - t0
            c.close()
            errors = [] if "tokens" in out else [out]
            snap = _scrape_metrics(srv.host, srv.port)
            wf = _sample_waterfall(srv.host, srv.port)
            return dt, errors, warm, snap, wf
        finally:
            srv.stop()

    def saved_delta(warm, snap):
        key = "serving.prefill_tokens_saved"
        return (snap.get("counters", {}).get(key, 0)
                - (warm or {}).get("counters", {}).get(key, 0))

    dt_cold, err_cold, warm_c, snap_c, _ = run(False)
    dt_warm, err_warm, warm_w, snap_w, wf_warm = run(True)
    if wf_warm:
        # A warm-cache admission's waterfall: prefill_ms prices only
        # the suffix, cached_tokens shows the skipped preamble
        # (rides inside extras.telemetry — tools/report.py).
        extras["prefix_waterfall"] = wf_warm
    extras["serving_prefix_clients"] = clients
    extras["serving_prefix_preamble_tokens"] = preamble_len
    extras["serving_prefix_tokens_saved"] = int(saved_delta(warm_w,
                                                            snap_w))
    extras["serving_prefix_hit_rate"] = snap_w.get("gauges", {}).get(
        "serving.prefix_hit_rate")
    if err_cold or err_warm:
        extras["serving_prefix_errors"] = [
            str(e)[:120] for e in (err_cold + err_warm)[:4]]
    ratio = None
    for tag, warm_s, snap_s in (("cold", warm_c, snap_c),
                                ("warm", warm_w, snap_w)):
        h = _hist_delta(warm_s, snap_s, "serving.ttft_ms")
        if h:
            p50 = histogram_quantile(h, 0.50)
            p99 = histogram_quantile(h, 0.99)
            extras[f"serving_prefix_{tag}_ttft_p50_ms"] = (
                round(p50, 3) if p50 else None)
            extras[f"serving_prefix_{tag}_ttft_p99_ms"] = (
                round(p99, 3) if p99 else None)
    p50c = extras.get("serving_prefix_cold_ttft_p50_ms")
    p50w = extras.get("serving_prefix_warm_ttft_p50_ms")
    if p50c and p50w:
        ratio = round(p50c / p50w, 4)
    elif dt_warm > 0:
        # Histogram-bucket degenerate case (both p50s in the lowest
        # bucket): fall back to wall-clock batch time, same workload.
        ratio = round(dt_cold / dt_warm, 4)
    extras["serving_prefix_ttft_vs_cold"] = ratio
    return ratio, ratio


def _bench_tp_mlp(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        m, hidden, inter = 2048, 4096, 12288 // max(n, 8) * n
        iters = (16, 48)
    else:
        m, hidden, inter = 256, 256, 512
        iters = (2, 4)

    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(mode):
        def f(x, p):
            y = mlp(p, x, mode=mode).astype(jnp.float32)
            scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
            return (y * scale).astype(jnp.bfloat16)
        return _args_step(f, params)

    def tune_mlp(layer, p, tag):
        """Sweep the layer's SWIGLU kernel eagerly BEFORE timing
        (winner disk-caches for the driver's run); the timed path then
        rides the tuned config through the ctx autotune cache consult.
        Only ag_ctx: the swiglu is 2/3 of the layer FLOPs and each
        extra sweep costs ~4 min of cold Mosaic compiles on chip — the
        down-proj gemm_rs keeps its (24 MB-budget) default tiles."""
        import dataclasses
        try:
            layer.ag_ctx = dataclasses.replace(layer.ag_ctx,
                                               autotune=True)
            jax.block_until_ready(layer(p, x0, mode="ag_rs"))
        except Exception as e:  # noqa: BLE001
            extras[f"{tag}_tune_error"] = _err(e)

    if on_tpu:
        tune_mlp(mlp, params, "tp_mlp")
    t_fused = perf_func_chained(make_step("ag_rs"), x0, iters)
    t_base = perf_func_chained(make_step("xla"), x0, iters)
    extras["tp_mlp_fused_ms"] = round(t_fused, 4)
    extras["tp_mlp_xla_ms"] = round(t_base, 4)
    extras["tp_mlp_vs_xla"] = round(t_base / t_fused, 4)
    # The MLP's fused path rides the ag_swiglu op (2/3 of layer FLOPs)
    # — that is the label the eager profiled dispatch runs under.
    _profile_measured_overlap(
        extras, "tp_mlp", "ag_swiglu",
        lambda: mlp(params, x0, mode="ag_rs"))

    if on_tpu:
        # Realistic per-chip width (the reference's MLP bench runs
        # ~3456 per GPU — e2e_dense.md:21; the primary line above keeps
        # per-chip 1536 for cross-round comparability).
        mlp_big = TPMLP(hidden, 3072 * max(n, 1), mesh=mesh, axis="tp",
                        dtype=jnp.bfloat16)
        params_b = mlp_big.init(jax.random.PRNGKey(2))
        tune_mlp(mlp_big, params_b, "tp_mlp_big")

        def make_step_big(mode):
            def f(x, p):
                y = mlp_big(p, x, mode=mode).astype(jnp.float32)
                scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
                return (y * scale).astype(jnp.bfloat16)
            return _args_step(f, params_b)

        tb_f = perf_func_chained(make_step_big("ag_rs"), x0, iters)
        tb_x = perf_func_chained(make_step_big("xla"), x0, iters)
        extras["tp_mlp_big_fused_ms"] = round(tb_f, 4)
        extras["tp_mlp_big_xla_ms"] = round(tb_x, 4)
        extras["tp_mlp_big_vs_xla"] = round(tb_x / tb_f, 4)
    return t_fused, t_base / t_fused


#: (name, hidden, heads/chip, kv/chip, head_dim, inter/chip) — Qwen3
#: configs divided by TP8 (VERDICT r3 next-5; reference e2e_dense.md
#: runs Qwen3-32B TP8, mega_triton_kernel.md runs 8B+32B TP8).
_LAYER_SLICES = {
    "layer_8b": ("qwen3_8b_tp8", 4096, 4, 1, 128, 1536),
    "layer_32b": ("qwen3_32b_tp8", 5120, 8, 1, 128, 3200),
}


def _bench_layer(which, mesh, n, on_tpu, extras):
    """One decoder layer (attn + mlp) at a reference model's per-chip
    TP8 slice dims, prefill M=2048 and decode M=128, fused vs XLA —
    the lines comparable to e2e_dense.md:21-23 and :34-36. Also emits
    attention-only prefill/decode ms (VERDICT r3 missing-5)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.layers import TPAttn, precompute_rope_cache
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.utils import perf_func_chained

    tag, h, nq, nkv, d, inter = _LAYER_SLICES[which]
    if not on_tpu:
        h, nq, nkv, d, inter = 128, 4, 2, 32, 256
    # world=1 runs the per-chip slice; on a real slice multiply back.
    nq, nkv, inter = nq * n, nkv * n, inter * n
    attn = TPAttn(h, nq, nkv, d, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    mlp = TPMLP(h, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    pa = attn.init(jax.random.PRNGKey(0))
    pm = mlp.init(jax.random.PRNGKey(1))
    t_cache = 512
    rope = precompute_rope_cache(d, t_cache)

    for phase, (b, s, fused_mode, xla_mode) in {
            "prefill": ((16, 128, "ag_rs", "xla") if on_tpu
                        else (2, 8, "ag_rs", "xla")),
            "decode": ((128, 1, "gemm_ar", "xla_ar") if on_tpu
                       else (4, 1, "gemm_ar", "xla_ar"))}.items():
        m = b * s
        sharded_in = {"ag_rs": True, "xla": True}.get  # row-sharded x
        pos = (jnp.tile(jnp.arange(s), (b, 1)) if phase == "prefill"
               else jnp.full((b, 1), 256, jnp.int32))
        offset = jnp.int32(0 if phase == "prefill" else 256)
        cache = tuple(
            jax.device_put(jnp.zeros((b, t_cache, nkv, d), jnp.bfloat16),
                           NamedSharding(mesh, P(None, None, "tp")))
            for _ in range(2))

        def make_step(mode, attn_only=False):
            sh = (NamedSharding(mesh, P("tp")) if sharded_in(mode)
                  else NamedSharding(mesh, P()))

            def f(x, pa, pm, kc, vc):
                a_out, _ = attn(pa, x, pos, rope, (kc, vc), offset,
                                mode=mode)
                y = x + a_out
                if not attn_only:
                    y = y + mlp(pm, y, mode=mode)
                yf = y.astype(jnp.float32)
                scale = 8.0 / jnp.maximum(
                    jnp.sqrt(jnp.mean(yf * yf)), 1e-3)
                return (yf * scale).astype(jnp.bfloat16)
            x0 = jax.device_put(
                jax.random.normal(jax.random.PRNGKey(2), (m, h),
                                  jnp.float32).astype(jnp.bfloat16), sh)
            return _args_step(f, pa, pm, *cache), x0

        iters = (8, 24) if on_tpu else (2, 4)
        res = {}
        for label, mode in (("fused", fused_mode), ("xla", xla_mode)):
            try:
                step, x0 = make_step(mode)
                res[label] = perf_func_chained(step, x0, iters)
                extras[f"{which}_{phase}_{label}_ms"] = round(res[label], 4)
            except Exception as e:  # noqa: BLE001 — isolate per mode
                extras[f"{which}_{phase}_{label}_error"] = _err(e)
        if "fused" in res and "xla" in res:
            extras[f"{which}_{phase}_vs_xla"] = round(
                res["xla"] / res["fused"], 4)
        # Attention-only line (fused mode): reference has attn rows.
        try:
            step, x0 = make_step(fused_mode, attn_only=True)
            extras[f"{which}_{phase}_attn_ms"] = round(
                perf_func_chained(step, x0, iters), 4)
        except Exception as e:  # noqa: BLE001
            extras[f"{which}_{phase}_attn_error"] = _err(e)
    extras[which + "_dims"] = tag
    return extras.get(f"{which}_prefill_fused_ms"), extras.get(
        f"{which}_prefill_vs_xla")


def _bench_overlap(mesh, n, on_tpu, extras):
    """DMA-under-MXU overlap proxy for the hbm ag_gemm kernel
    (VERDICT r3 next-7; BASELINE.md north star >=90%).

    Methodology (recorded in ``overlap_method``): the kernel pipelines
    HBM->VMEM panel DMAs under MXU dot tiles. We measure (a) t_mxu —
    the same-shape plain dot from timing_selfcheck's calibration
    (VMEM-pipelined by XLA, i.e. pure compute throughput), (b) t_dma —
    the kernel's total panel traffic at the chip's measured HBM
    bandwidth (probed with a jit copy of an equal-byte buffer), and
    (c) t_fused — the measured fused kernel time. Overlap = fraction
    of the smaller phase hidden under the larger:
        (t_mxu + t_dma - t_fused) / min(t_mxu, t_dma).
    This is a derived proxy, not a trace decomposition: at world=1 the
    ring degenerates to local panel streaming, so the number reports
    kernel-internal DMA/compute overlap (the schedule that also drives
    the world=8 ring, whose structure is validated in interpret mode)."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.runtime.utils import perf_func_chained
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    item = 2

    # (a) pure-compute reference: plain dot, same shape.
    a = jax.random.normal(jax.random.PRNGKey(0), (m, k),
                          jnp.float32).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (k, nn),
                          jnp.float32).astype(jnp.bfloat16)

    def dot_step(x, bb):
        y = jnp.dot(x, bb, preferred_element_type=jnp.float32)
        return (y[:, :k] * 1e-3).astype(jnp.bfloat16)
    t_mxu = perf_func_chained(_args_step(dot_step, b), a, (8, 24))

    # (b) HBM bandwidth probe: stream an equal-byte buffer through a
    # copy (read + write, like a DMA).
    vol_bytes = item * (m * k + k * nn + m * nn)   # A in, B in, C out
    probe_elems = max(vol_bytes // 2, 1 << 20)
    big = jnp.ones((probe_elems,), jnp.bfloat16)

    def copy_step(x):
        return x * jnp.asarray(1.0001, jnp.bfloat16)
    t_copy = perf_func_chained(_args_step(copy_step), big, (8, 24))
    hbm_gbps = 2.0 * probe_elems * item / (t_copy * 1e-3) / 1e9
    t_dma = vol_bytes / (hbm_gbps * 1e9) * 1e3   # ms

    # (c) the fused kernel, forced down the hbm (streaming) variant.
    import dataclasses
    ctx = create_ag_gemm_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    ctx = dataclasses.replace(ctx, variant="hbm")
    a0 = jax.device_put(a, NamedSharding(mesh, P("tp")))
    bb = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))

    def fused_step(x, w):
        return _chain_fold(ag_gemm(x, w, ctx, impl="pallas"), m, k)
    t_fused = perf_func_chained(_args_step(fused_step, bb), a0, (8, 24))

    # (d) the same three ingredients for the hbm GEMM-RS kernel, so the
    # north-star overlap metric exists for BOTH flagship fused ops.
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    rs_ctx = dataclasses.replace(
        create_gemm_rs_context(mesh, "tp",
                               interpret=None if not on_tpu else False),
        variant="hbm")
    a0_rs = jax.device_put(a, NamedSharding(mesh, P(None, "tp")))
    b_rs = jax.device_put(b, NamedSharding(mesh, P("tp")))

    def rs_fused_step(x, w):
        return _chain_fold(gemm_rs(x, w, rs_ctx, impl="pallas"), m, k)
    try:
        t_fused_rs = perf_func_chained(_args_step(rs_fused_step, b_rs),
                                       a0_rs, (8, 24))
        extras["overlap_gemm_rs_t_fused_ms"] = round(t_fused_rs, 4)
    except Exception as e:  # noqa: BLE001 — keep the ag_gemm evidence
        t_fused_rs = None
        extras["overlap_gemm_rs_error"] = _err(e)

    extras["overlap_t_mxu_ms"] = round(t_mxu, 4)
    extras["overlap_t_dma_ms"] = round(t_dma, 4)
    extras["overlap_t_fused_ms"] = round(t_fused, 4)
    extras["overlap_hbm_gbps"] = round(hbm_gbps, 1)
    if not on_tpu:
        # On CPU every ingredient is a fiction (interpret-mode kernel
        # time, a host-memcpy "HBM" probe): refusing to print an
        # overlap pct beats publishing 0.0%-with-13-GB/s placeholders
        # (VERDICT r4 missing-4). The CPU run still validates the
        # machinery end-to-end via the ingredient keys above.
        extras["overlap_requires_chip"] = True
        return None, None

    def derived_pct(t_f):
        denom = min(t_mxu, t_dma)
        if t_f is None or denom <= 0:
            return None
        return round(max(min((t_mxu + t_dma - t_f) / denom * 100.0,
                             100.0), 0.0), 1)

    pct = derived_pct(t_fused)
    if pct is not None:
        extras["ag_gemm_overlap_pct"] = pct
        extras["comms.ag_gemm.overlap_pct"] = pct
    pct_rs = derived_pct(t_fused_rs)
    if pct_rs is not None:
        extras["comms.gemm_rs.overlap_pct"] = pct_rs
    extras["overlap_method"] = (
        "derived: (t_mxu + t_dma - t_fused)/min(t_mxu, t_dma); t_mxu = "
        "plain same-shape dot, t_dma = kernel panel bytes / probed HBM "
        "BW; world=1 => kernel-internal DMA/compute overlap. comms.* "
        "keys mirror the obs gauge names (model-derived gauges ride in "
        "extras.telemetry; these are the measured counterparts)")
    return pct, None


def _bench_train(mesh, n, on_tpu, extras):
    """Training-step throughput (beyond-reference: the reference is
    inference-only, SURVEY §2.9). Times the fused ag_rs train step —
    whose backward rides the transpose fused kernels (ops/autodiff.py)
    — against the xla-collective baseline; reports tokens/s."""
    import jax
    import jax.numpy as jnp
    from triton_dist_tpu.models import DenseLLM, ModelConfig
    from triton_dist_tpu.models.train import make_train_step
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        cfg = ModelConfig(hidden_size=2048, intermediate_size=8192,
                          num_hidden_layers=4, num_attention_heads=16,
                          num_key_value_heads=8, head_dim=128,
                          vocab_size=32768, max_position_embeddings=1024,
                          dtype=jnp.bfloat16)
        b, s, iters = 4, 512, (4, 12)
    else:
        cfg = ModelConfig(hidden_size=128, intermediate_size=256,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, head_dim=64,
                          vocab_size=256, max_position_embeddings=64,
                          dtype=jnp.float32)
        b, s, iters = 2, 8, (2, 4)
    batch = {"input_ids": jax.random.randint(
        jax.random.PRNGKey(7), (b, s), 0, cfg.vocab_size, jnp.int32)}

    times = {}
    for key, mode, impl in (("fused", "ag_rs", "pallas"),
                            ("xla", "xla", "xla")):
        model = DenseLLM(cfg, mesh=mesh, axis="tp", impl=impl,
                         fwd_mode=mode)
        params = model.init(jax.random.PRNGKey(0))
        # donate=False: the perf chain re-perturbs the same initial
        # buffers across runs, which donation would invalidate.
        run_step, init_opt = make_train_step(model, mode=mode,
                                             donate=False)
        opt0 = init_opt(params)

        def step(carry):
            p, o = carry
            p, o, _ = run_step(p, o, batch)
            return (p, o)

        times[key] = perf_func_chained(step, (params, opt0), iters)

    extras["train_fused_ms"] = round(times["fused"], 4)
    extras["train_xla_ms"] = round(times["xla"], 4)
    extras["train_vs_xla"] = round(times["xla"] / times["fused"], 4)
    extras["train_tokens_per_s"] = round(b * s / times["fused"] * 1e3, 1)
    if not on_tpu:
        # Interpret-mode kernels vs compiled XLA: the ratio prices the
        # interpreter, not the kernels (VERDICT r4 weak-5). Labeled so
        # no reader mistakes the CPU tokens/s for a capability number.
        extras["train_numbers_are_interpret_mode"] = True
    return times["fused"], times["xla"] / times["fused"]


def _n_measured(ex: dict) -> int:
    """Count measured-metric keys in a checkpoint's extras."""
    return sum(1 for k, v in ex.items()
               if isinstance(v, (int, float))
               and k.endswith(("_ms", "_tflops", "_ratio",
                               "_tokens_per_s", "_pct", "_bytes")))


def _is_tpu_checkpoint(ex: dict) -> int:
    """1 when a checkpoint's extras were measured on a TPU (its
    ``device_kind`` is recorded by every bench child), else 0. The
    fallback scan ranks this ABOVE recency: a same-morning CPU
    validation run must not outrank the TPU run whose numbers are the
    actual evidence (VERDICT r5 fact 1 — BENCH_r05.json shipped a CPU
    checkpoint while a TPU checkpoint existed)."""
    return 1 if "tpu" in str(ex.get("device_kind", "")).lower() else 0


def _fallback_scan_paths() -> list:
    """Every path a bench may have checkpointed to, deduplicated: the
    active TDT_BENCH_PROGRESS target, the default, and both watcher
    files (review r5b-2). Module-level so tests can patch it."""
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = []
    for path in (
            _progress_path(),
            os.path.join(here, ".bench_progress_latest.json"),
            os.path.join(here, ".bench_progress_watcher.json"),
            os.path.join(here, ".bench_progress_watcher_headline.json")):
        if path not in candidates:
            candidates.append(path)
    return candidates


def main():
    _resilience_env()
    extras: dict = {}
    result = {"metric": "ag_gemm_tflops", "value": None, "unit": "TFLOPS",
              "vs_baseline": None, "extras": extras}
    # Validate part selectors BEFORE the probe and the checkpoint
    # clear: a typo'd TDT_BENCH_PARTS must fail loud without first
    # erasing the previous run's evidence (and must fail even when the
    # tunnel is wedged and the probe branch would return early).
    bad = [s for s in os.environ.get("TDT_BENCH_PARTS", "").split(",")
           if s and s not in _PART_ORDER]
    if bad:
        raise SystemExit(f"unknown TDT_BENCH_PARTS entries {bad}; "
                         f"known: {list(_PART_ORDER)}")
    only_env = [s for s in os.environ.get("TDT_BENCH_ONLY", "").split(",")
                if s]
    if not only_env and os.environ.get("TDT_BENCH_SUBPROC", "1") != "0":
        # Full-run (parent) mode: probe first with a hard deadline —
        # never spawn children into a wedged tunnel — then orchestrate;
        # the parent itself never touches the tunnel so a hung Mosaic
        # compile cannot take down the run.
        if os.environ.get("TDT_BENCH_CPU") != "1" \
                and not (_probe_backend_subprocess(75.0)
                         or _probe_backend_subprocess(75.0)):
            extras["probe_failed"] = True
            # Carry the NEWEST prior checkpoint (a wedged tunnel at
            # round end must not zero out knowledge of the last good
            # run). Its headline metric IS promoted to the top-level
            # fields — a None value reads as "never measured" when a
            # full on-chip table exists — but only with the explicit
            # from_prior_run label carrying age + source, so the line
            # can never pass off old numbers as a fresh run. The
            # watcher's bench writes to a dedicated path, so scan both.
            # Among candidates the NEWEST one that carries at least one
            # measured metric wins — with TPU checkpoints ranked above
            # CPU ones first (VERDICT r5 fact 1: the score used to be
            # device-kind-blind, so a newer CPU validation run outranked
            # the same morning's TPU run and BENCH_r05.json shipped CPU
            # numbers as the fallback). Plain newest-wins would let a
            # wedged run's near-empty "init" checkpoint mask the good
            # run it followed, while metric-count-wins would let an
            # arbitrarily stale full run outrank this round's fresh
            # headline evidence (review r5a-1, r5b-1).
            best = (-1, -1, -1.0)  # (has_measured, is_tpu, ts)
            for path in _fallback_scan_paths():
                try:
                    with open(path) as f:
                        prior = json.load(f)
                    ts = float(prior.get("ts", 0))
                    prior_extras = prior.get("extras", {})
                    n_measured = _n_measured(prior_extras)
                    score = (1 if n_measured else 0,
                             _is_tpu_checkpoint(prior_extras), ts)
                    if score > best:
                        best = score
                        extras["prior_run"] = prior_extras
                        extras["prior_run_age_s"] = round(time.time() - ts)
                        extras["prior_run_path"] = os.path.basename(path)
                        extras["prior_run_n_measured"] = n_measured
                        extras["prior_run_device_kind"] = prior_extras.get(
                            "device_kind")
                except (OSError, ValueError):
                    pass
            if extras.get("prior_run_n_measured"):
                sel = _select_result(extras["prior_run"])
                if sel["value"] is not None:
                    # The top-level ``value`` stays null — this run
                    # measured NOTHING, and a label-blind consumer
                    # reading metric/value must not mistake the last
                    # good run's number for a fresh one (ADVICE r5
                    # low). The prior evidence is carried under
                    # explicitly-prior names instead: ``prior_value``
                    # + a "(prior)"-suffixed metric label + the
                    # from_prior_run provenance (age + source file).
                    result.update(metric=sel["metric"] + " (prior)",
                                  unit=sel["unit"])
                    result["prior_value"] = sel["value"]
                    result["prior_vs_baseline"] = sel["vs_baseline"]
                    result["from_prior_run"] = {
                        "age_s": extras["prior_run_age_s"],
                        "path": extras["prior_run_path"]}
            print(json.dumps(result))
            return
        # Fresh run: clear any stale checkpoint so a run that dies
        # before its first part can't pass off old metrics as its own.
        _checkpoint_extras(extras, "init")
        _run_parts_in_children(extras)
        _finalize_checks(extras)
        extras["bench_wall_s"] = round(time.monotonic() - _T0, 1)
        _checkpoint_extras(extras, "final")
        print(json.dumps(_select_result(extras)))
        return
    try:
        # Inline / TDT_BENCH_ONLY mode: clear any stale checkpoint up
        # front — a run that wedges before its first part must not
        # leave the previous run's metrics in the file as its own
        # (review r4b-2; the parent branch above does the same).
        _checkpoint_extras(extras, "init")
        import numpy as np
        devices = _init_backend()
        import jax
        from jax.sharding import Mesh
        from triton_dist_tpu.runtime.platform import is_tpu
        on_tpu = is_tpu()
        n = len(devices) if on_tpu else 1
        mesh = Mesh(np.array(devices[:n]), ("tp",))
        extras["n_devices"] = n
        extras["device_kind"] = getattr(devices[0], "device_kind", "?")

        # Telemetry rides along for free: the collective wrappers the
        # benches exercise count their invocations + payload bytes
        # (trace-time under jit — per program build) into the obs
        # registry; the cumulative snapshot lands under
        # extras.telemetry and tools/report.py renders it. With
        # TDT_TRACE=1, enable() also arms the event tracer — the
        # dispatch timeline (op instants, ring-schedule chunk events)
        # then dumps as a flight record at the end of the run.
        from triton_dist_tpu import obs
        from triton_dist_tpu.obs import flight as _flight
        from triton_dist_tpu.obs import trace as _trace
        obs.enable()

        if on_tpu and (not only_env or "ag_gemm" in only_env):
            try:
                from triton_dist_tpu.runtime.utils import timing_selfcheck
                extras["timing_selfcheck"] = timing_selfcheck()
            except Exception as e:  # noqa: BLE001
                extras["timing_selfcheck_error"] = _err(e)

        # TDT_BENCH_ONLY: comma-separated sub-benchmark names — one part
        # per short-lived process on the flaky tunnel, so one hung
        # Mosaic compile can't take the other metrics down with it.
        benches = (
            ("ag_gemm", lambda: _bench_ag_gemm(mesh, n, on_tpu, extras)),
            ("gemm_rs", lambda: _bench_gemm_rs(mesh, n, on_tpu, extras)),
            ("gemm_ar", lambda: _bench_gemm_ar(mesh, n, on_tpu, extras)),
            ("flash_decode",
             lambda: _bench_flash_decode(mesh, n, on_tpu, extras)),
            ("tp_mlp", lambda: _bench_tp_mlp(mesh, n, on_tpu, extras)),
            ("layer_8b",
             lambda: _bench_layer("layer_8b", mesh, n, on_tpu, extras)),
            ("layer_32b",
             lambda: _bench_layer("layer_32b", mesh, n, on_tpu, extras)),
            ("overlap", lambda: _bench_overlap(mesh, n, on_tpu, extras)),
            ("moe_ag_gg",
             lambda: _bench_ag_group_gemm(mesh, n, on_tpu, extras)),
            ("mega",
             lambda: _bench_mega_vs_engine(mesh, n, on_tpu, extras)),
            ("serving",
             lambda: _bench_serving(mesh, n, on_tpu, extras)),
            ("serving_mega",
             lambda: _bench_serving_mega(mesh, n, on_tpu, extras)),
            ("serving_spec",
             lambda: _bench_serving_spec(mesh, n, on_tpu, extras)),
            ("serving_fleet",
             lambda: _bench_serving_fleet(mesh, n, on_tpu, extras)),
            ("serving_router",
             lambda: _bench_serving_router(mesh, n, on_tpu, extras)),
            ("serving_history",
             lambda: _bench_serving_history(mesh, n, on_tpu, extras)),
            ("serving_disagg",
             lambda: _bench_serving_disagg(mesh, n, on_tpu, extras)),
            ("prefix",
             lambda: _bench_prefix(mesh, n, on_tpu, extras)),
            ("sp_attn",
             lambda: _bench_sp_attention(mesh, n, on_tpu, extras)),
            ("train", lambda: _bench_train(mesh, n, on_tpu, extras)),
        )
        assert {b[0] for b in benches} == set(_PART_ORDER), \
            "benches tuple and _PART_ORDER drifted"
        only = only_env
        bad = [s for s in only if s not in {b[0] for b in benches}]
        if bad:  # a typo must not turn into a silently empty bench;
            # SystemExit bypasses the blanket except below → rc != 0.
            raise SystemExit(
                f"unknown TDT_BENCH_ONLY entries {bad}; "
                f"known: {[b[0] for b in benches]}")
        wf_acc: dict = {}
        for name, fn in benches:
            if only and name not in only:
                continue
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — partial over rc!=0
                extras[name + "_error"] = _err(e)
            tel = obs.snapshot()
            if "disagg_snapshot" in extras:
                # The serving_disagg part's private-registry disagg.*
                # metrics merge into the part telemetry (report.py
                # "disagg" section reads top-level counters /
                # histograms); extras stays a flat scalar map for the
                # regress gate.
                from triton_dist_tpu.obs import merge_snapshots
                tel = merge_snapshots(
                    [tel, extras.pop("disagg_snapshot")])
            if _trace.enabled():
                tel["trace"] = _trace.stats()
            for k in ("serving_waterfall", "prefix_waterfall"):
                # Sampled request-attribution waterfalls live ONLY
                # under extras.telemetry (report.py "request
                # waterfalls") — extras itself stays a flat scalar
                # map for the regress gate.
                if k in extras:
                    wf_acc[k] = extras.pop(k)
            if wf_acc:
                tel["waterfalls"] = dict(wf_acc)
            if "fleet_snapshot" in extras:
                # The serving_fleet part's merged snapshot rides the
                # same way (report.py "fleet" section); extras stays
                # a flat scalar map for the regress gate.
                fleet_acc = extras.pop("fleet_snapshot")
            else:
                fleet_acc = (extras.get("telemetry") or {}).get("fleet")
            if fleet_acc:
                tel["fleet"] = fleet_acc
            if "router_snapshot" in extras:
                # The serving_router part's status snapshot likewise
                # (report.py "router" section).
                router_acc = extras.pop("router_snapshot")
            else:
                router_acc = (extras.get("telemetry")
                              or {}).get("router")
            if router_acc:
                tel["router"] = router_acc
            if "history_snapshot" in extras:
                # The serving_history part's sampled-series snapshot
                # likewise (report.py "history" section).
                hist_acc = extras.pop("history_snapshot")
            else:
                hist_acc = (extras.get("telemetry")
                            or {}).get("history")
            if hist_acc:
                tel["history"] = hist_acc
            if any(tel.values()):
                extras["telemetry"] = tel
            _checkpoint_extras(extras, name)

        if _trace.enabled():
            # The run's timeline as an artifact: the full ring window,
            # path surfaced next to the numbers it explains.
            p = _flight.maybe_dump("bench", last_s=1e9)
            if p:
                extras["trace_path"] = p
                tel = extras.get("telemetry")
                if tel is not None:
                    tel["trace"] = _trace.stats()
        _finalize_checks(extras)
        result = _select_result(extras)
    except Exception as e:  # noqa: BLE001 — emit partial JSON, never rc!=0
        extras["fatal"] = _err(e)
        _checkpoint_extras(extras, "fatal")

    print(json.dumps(result))
    if only_env:
        # Child mode (one sub-benchmark per process): hard-exit to skip
        # JAX backend teardown. Teardown waits on the tunnel and has
        # been observed to linger minutes-to-forever on a wedged remote
        # (tpu_smoke 07-31); results are checkpointed + printed already.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(0)


if __name__ == "__main__":
    main()
