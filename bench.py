"""Benchmark entry point (driver-run on real TPU hardware).

Round-2 contract (VERDICT.md "what's weak" 1): this script must NEVER let
a backend failure kill the perf story — backend init is retried with
backoff and every sub-benchmark failure degrades to a field in the JSON
rather than rc!=0.

What it benches (BASELINE.md north star: per-op TFLOPS + overlap
efficiency; reference headline e2e_dense.md:21):
  * ``ag_gemm``  — fused AllGather-GEMM Pallas kernel vs the XLA
    all_gather+dot baseline, TFLOPS per chip.
  * ``gemm_rs``  — fused GEMM-ReduceScatter vs XLA dot+psum_scatter.
  * ``tp_mlp``   — the round-1 headline metric (fused MLP fwd ms), kept
    for cross-round comparability.
On a single chip (the tunneled bench environment) the collective parts
collapse, so the numbers measure Mosaic-kernel vs XLA compute quality;
on a real slice the same code measures overlap.

Timing: the tunneled chip executes lazily and dedupes unread results, so
each mode is timed as a self-chained step and the per-step cost is the
slope between two chained runs (runtime/utils.perf_func_chained).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"extras"}. ``vs_baseline`` > 1.0 means the fused/Pallas path beats the
XLA baseline on the same hardware.
"""

from __future__ import annotations

import json
import time
import traceback


def _probe_backend_subprocess(timeout_s: float) -> bool:
    """Probe backend init in a THROWAWAY subprocess with a hard deadline.

    Two failure modes make in-process retry useless (round-1 postmortem):
    the tunneled PJRT plugin can *hang* in make_c_api_client (no
    exception ever reaches a retry loop), and jax caches backend init
    failures so a second in-process jax.devices() cannot recover. A
    subprocess gives both a kill-able deadline and a fresh cache."""
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(len(d))"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _init_backend(retries: int = 3, probe_timeout_s: float = 240.0,
                  backoff_s: float = 30.0):
    """Return jax.devices(), but only attempt in-process init after a
    subprocess probe has confirmed the backend actually comes up."""
    for attempt in range(retries):
        if _probe_backend_subprocess(probe_timeout_s):
            import jax
            return jax.devices()
        if attempt < retries - 1:
            time.sleep(backoff_s * (attempt + 1))
    raise RuntimeError(
        f"backend never initialized within {retries} probe attempts")


def _bench_ag_gemm(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    from triton_dist_tpu.runtime.utils import perf_func_chained

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_ag_gemm_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))

    def make_step(impl):
        @jax.jit
        def step(a):
            c = ag_gemm(a, b, ctx, impl=impl)
            # fold C back to A's shape so the step chains; the fold cost
            # is identical across impls.
            return c[:, :k].astype(jnp.float32).astype(jnp.bfloat16) * 1e-3
        return step

    flops = 2.0 * m * k * nn  # with column sharding each chip does
    # 2*M*K*N/n flops; report per-chip TFLOPS.
    t_pallas = perf_func_chained(make_step("pallas"), a0, (8, 24))
    t_xla = perf_func_chained(make_step("xla"), a0, (8, 24))

    # Autotuned config (eager sweep caches by shape; VERDICT r1 item 5).
    import dataclasses
    from triton_dist_tpu.ops import allgather_gemm as agm
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = agm.ag_gemm(a0, b, tctx, impl="pallas")   # eager → sweep
        tuned_step = jax.jit(
            lambda x: (agm.ag_gemm(x, b, tctx, impl="pallas")
                       [:, :k].astype(jnp.float32).astype(jnp.bfloat16)
                       * 1e-3))
        t_tuned = perf_func_chained(tuned_step, a0, (8, 24))
        key_t = next(iter(k2 for k2 in agm._TUNED
                          if k2[:2] == (m, k)), None)
        extras["ag_gemm_tuned_ms"] = round(t_tuned, 4)
        extras["ag_gemm_tuned_cfg"] = agm._TUNED.get(key_t)
        t_pallas = min(t_pallas, t_tuned)
    except Exception:  # noqa: BLE001
        extras["ag_gemm_tune_error"] = \
            traceback.format_exc().strip().splitlines()[-1][:160]

    tflops = flops / max(n, 1) / (t_pallas * 1e-3) / 1e12
    extras["ag_gemm_pallas_ms"] = round(t_pallas, 4)
    extras["ag_gemm_xla_ms"] = round(t_xla, 4)
    extras["ag_gemm_tflops"] = round(tflops, 2)
    extras["ag_gemm_vs_xla"] = round(t_xla / t_pallas, 4)
    return tflops, t_xla / t_pallas


def _bench_gemm_rs(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    from triton_dist_tpu.runtime.utils import perf_func

    m, k, nn = (2048, 4096, 4096) if on_tpu else (64, 128, 128)
    ctx = create_gemm_rs_context(mesh, "tp",
                                 interpret=None if not on_tpu else False)
    a0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (k, nn), jnp.float32
                          ).astype(jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    # gemm_rs changes shape (M, K) -> (M/w rows), so self-chaining is not
    # possible; time with a fixed input instead (output read per step).
    t_ms = {}
    for impl in ("pallas", "xla"):
        f = jax.jit(lambda a, impl=impl: gemm_rs(a, b, ctx, impl=impl))
        _ = jax.block_until_ready(f(a0))
        _, ms = perf_func(lambda f=f: f(a0), iters=16, warmup_iters=4)
        t_ms[impl] = ms

    import dataclasses
    from triton_dist_tpu.ops import gemm_reduce_scatter as grs
    try:
        tctx = dataclasses.replace(ctx, autotune=True)
        _ = grs.gemm_rs(a0, b, tctx, impl="pallas")   # eager → sweep
        ft = jax.jit(lambda a: grs.gemm_rs(a, b, tctx, impl="pallas"))
        _ = jax.block_until_ready(ft(a0))
        _, ms_t = perf_func(lambda: ft(a0), iters=16, warmup_iters=4)
        extras["gemm_rs_tuned_ms"] = round(ms_t, 4)
        extras["gemm_rs_tuned_cfg"] = next(
            (v for kk, v in grs._TUNED.items() if kk[0] == m), None)
        t_ms["pallas"] = min(t_ms["pallas"], ms_t)
    except Exception:  # noqa: BLE001
        extras["gemm_rs_tune_error"] = \
            traceback.format_exc().strip().splitlines()[-1][:160]
    flops = 2.0 * m * k * nn
    tflops = flops / max(n, 1) / (t_ms["pallas"] * 1e-3) / 1e12
    extras["gemm_rs_pallas_ms"] = round(t_ms["pallas"], 4)
    extras["gemm_rs_xla_ms"] = round(t_ms["xla"], 4)
    extras["gemm_rs_tflops"] = round(tflops, 2)
    extras["gemm_rs_vs_xla"] = round(t_ms["xla"] / t_ms["pallas"], 4)
    return tflops, t_ms["xla"] / t_ms["pallas"]


def _bench_tp_mlp(mesh, n, on_tpu, extras):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    from triton_dist_tpu.runtime.utils import perf_func_chained

    if on_tpu:
        m, hidden, inter = 2048, 4096, 12288 // max(n, 8) * n
        iters = (16, 48)
    else:
        m, hidden, inter = 256, 256, 512
        iters = (2, 4)

    mlp = TPMLP(hidden, inter, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x0 = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(1), (m, hidden), jnp.bfloat16),
        NamedSharding(mesh, P("tp")))

    def make_step(mode):
        @jax.jit
        def step(x):
            y = mlp(params, x, mode=mode).astype(jnp.float32)
            scale = 8.0 / jnp.maximum(jnp.sqrt(jnp.mean(y * y)), 1e-3)
            return (y * scale).astype(jnp.bfloat16)
        return step

    t_fused = perf_func_chained(make_step("ag_rs"), x0, iters)
    t_base = perf_func_chained(make_step("xla"), x0, iters)
    extras["tp_mlp_fused_ms"] = round(t_fused, 4)
    extras["tp_mlp_xla_ms"] = round(t_base, 4)
    extras["tp_mlp_vs_xla"] = round(t_base / t_fused, 4)
    return t_fused, t_base / t_fused


def main():
    extras: dict = {}
    result = {"metric": "ag_gemm_tflops", "value": None, "unit": "TFLOPS",
              "vs_baseline": None, "extras": extras}
    try:
        import numpy as np
        devices = _init_backend()
        import jax
        from jax.sharding import Mesh
        from triton_dist_tpu.runtime.platform import is_tpu
        on_tpu = is_tpu()
        n = len(devices) if on_tpu else 1
        mesh = Mesh(np.array(devices[:n]), ("tp",))
        extras["n_devices"] = n
        extras["device_kind"] = getattr(devices[0], "device_kind", "?")

        for name, fn in (
                ("ag_gemm", lambda: _bench_ag_gemm(mesh, n, on_tpu, extras)),
                ("gemm_rs", lambda: _bench_gemm_rs(mesh, n, on_tpu, extras)),
                ("tp_mlp", lambda: _bench_tp_mlp(mesh, n, on_tpu, extras)),
        ):
            try:
                fn()
            except Exception:  # noqa: BLE001 — partial output over rc!=0
                extras[name + "_error"] = \
                    traceback.format_exc().strip().splitlines()[-1][:200]

        if "ag_gemm_tflops" in extras:
            result["value"] = extras["ag_gemm_tflops"]
            result["vs_baseline"] = extras["ag_gemm_vs_xla"]
        elif "gemm_rs_tflops" in extras:
            result = {"metric": "gemm_rs_tflops",
                      "value": extras["gemm_rs_tflops"], "unit": "TFLOPS",
                      "vs_baseline": extras["gemm_rs_vs_xla"],
                      "extras": extras}
        elif "tp_mlp_fused_ms" in extras:
            result = {"metric": "tp_mlp_fused_ms",
                      "value": extras["tp_mlp_fused_ms"], "unit": "ms",
                      "vs_baseline": extras["tp_mlp_vs_xla"],
                      "extras": extras}
    except Exception:  # noqa: BLE001 — emit partial JSON, never rc!=0
        extras["fatal"] = traceback.format_exc().strip().splitlines()[-1][:300]

    print(json.dumps(result))


if __name__ == "__main__":
    main()
