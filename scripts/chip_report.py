"""Assemble on-chip evidence into one judge-readable markdown table.

Run after the hardware watcher drains (or any manual chip session):

    python scripts/chip_report.py > CHIP_EVIDENCE_r5.md

Collects, without touching the tunnel:
- the newest streamed JSON line from each ``hw_*.out`` bench capture,
- every ``.bench_progress*.json`` checkpoint (ts, device kind, measured
  metric count),
- PASS/FAIL counts from ``tpu_smoke_r5*.log``.

Pure host-side I/O — safe to run while the tunnel is wedged.
"""

from __future__ import annotations

import glob
import json
import os
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_METRIC_SUFFIXES = ("_ms", "_tflops", "_ratio", "_tokens_per_s", "_pct",
                    "_bytes")


def _measured(extras: dict) -> dict:
    return {k: v for k, v in extras.items()
            if isinstance(v, (int, float)) and k.endswith(_METRIC_SUFFIXES)}


def _last_json_line(path: str) -> dict | None:
    best = None
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if line.startswith("{") and line.endswith("}"):
                    try:
                        best = json.loads(line)
                    except ValueError:
                        pass
    except OSError:
        return None
    return best


def main() -> None:
    # No generation timestamp and absolute (not relative) checkpoint
    # times: the output must be byte-stable when the underlying
    # evidence is unchanged, so the watcher's after-every-step commit
    # hook produces commits only when NEW evidence exists.
    print("# Chip evidence report")
    print(f"\nAssembled from `{ROOT}` (host-side files only).\n")

    print("## Bench captures (hw_*.out streamed JSON)\n")
    rows = []
    for path in sorted(glob.glob(os.path.join(ROOT, "hw_*.out"))
                       + glob.glob(os.path.join(ROOT, "artifacts", "hw_*.out"))):
        d = _last_json_line(path)
        if not d:
            continue
        e = d.get("extras", {})
        rows.append((os.path.basename(path), d.get("metric"),
                     d.get("value"), e.get("device_kind", "?"),
                     len(_measured(e)),
                     e.get("baseline_anomaly")))
    if rows:
        print("| file | headline metric | value | device | measured keys |"
              " anomaly |")
        print("|---|---|---|---|---|---|")
        for r in rows:
            print("| " + " | ".join(str(x) for x in r) + " |")
    else:
        print("(none found)")

    print("\n## Checkpoints (.bench_progress*.json)\n")
    print("| file | written | device | measured keys | last part |")
    print("|---|---|---|---|---|")
    for path in sorted(glob.glob(os.path.join(ROOT, ".bench_progress*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        e = d.get("extras", {})
        ts = time.strftime("%Y-%m-%d %H:%M:%S",
                           time.localtime(float(d.get("ts", 0))))
        print(f"| {os.path.basename(path)} | {ts} | "
              f"{e.get('device_kind', '?')} | "
              f"{len(_measured(e))} | {d.get('last_done', '?')} |")

    print("\n## Smoke logs (tpu_smoke_r5*.log)\n")
    print("| log | PASS | FAIL | TIMEOUT |")
    print("|---|---|---|---|")
    for path in sorted(glob.glob(os.path.join(ROOT, "tpu_smoke_r5*.log"))
                       + glob.glob(os.path.join(ROOT, "artifacts", "tpu_smoke_r5*.log"))):
        try:
            with open(path, errors="replace") as f:
                text = f.read()
        except OSError:
            continue
        print(f"| {os.path.basename(path)} | {text.count(' PASS')} | "
              f"{text.count(' FAIL')} | {text.count(' TIMEOUT')} |")


if __name__ == "__main__":
    main()
