"""Commit whatever chip evidence exists right now (host-side only).

Called by scripts/hw_watch.py after EVERY completed queue step (and
once more when the queue drains), so evidence is committed
incrementally — a tunnel window that opens and closes while nobody is
watching still leaves committed results even if a later step wedges
the tunnel again. Never touches the tunnel itself.

Committed set: the rendered CHIP_EVIDENCE_r5.md (best-effort — a
renderer failure must not block the raw data), every tpu_smoke_r5*.log
and hw_*.out capture, and the .bench_progress_watcher*.json
checkpoints (the durable bench evidence; gitignored by pattern, hence
``git add -f``).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> None:
    paths = []
    report = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "chip_report.py")],
        capture_output=True, text=True, cwd=ROOT)
    if report.returncode == 0:
        out_path = os.path.join(ROOT, "CHIP_EVIDENCE_r5.md")
        with open(out_path, "w") as f:
            f.write(report.stdout)
        paths.append(out_path)
    else:
        # The raw captures below still get committed.
        print("chip_report failed:", report.stderr[-500:], file=sys.stderr)

    # Run artifacts live under artifacts/ since ISSUE 5 (repo-root
    # strays are gitignored now); scan both for older runs' leftovers.
    for base in (ROOT, os.path.join(ROOT, "artifacts")):
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            if (name.startswith(("tpu_smoke_r5", "hw_")) and
                    name.endswith((".log", ".out")) and
                    name not in ("hw_watch.out", "hw_watch.log")):
                paths.append(os.path.join(base, name))
    paths.extend(sorted(glob.glob(
        os.path.join(ROOT, ".bench_progress_watcher*.json"))))

    subprocess.run(["git", "add", "-f", *paths], cwd=ROOT, check=True)
    r = subprocess.run(
        ["git", "commit", "-m",
         "Hardware evidence: watcher step output (auto-committed)\n\n"
         "No-Verification-Needed: evidence logs only"],
        cwd=ROOT, capture_output=True, text=True)
    out = (r.stdout or "") + (r.stderr or "")
    print(out.strip())
    if r.returncode != 0 and "nothing to commit" not in out \
            and "no changes added to commit" not in out \
            and "nothing added to commit" not in out:
        sys.exit(1)  # real failure (hooks, identity, lock) — surface it


if __name__ == "__main__":
    main()
