"""Capture a jax.profiler trace of one fused op vs its XLA golden on
the chip — the evidence backing a perf concession when a world=1
`vs_xla` ratio stays below 1.0 (VERDICT r4 next-8: ">=1.0x or
trace-backed concessions").

Usage (on a healthy tunnel, nothing else running on the host):

    python scripts/profile_op.py ag_gemm [outdir]

Writes a TensorBoard-loadable trace per impl under
``<outdir>/<op>_<impl>/`` (default outdir: ``profiles/``) plus a
one-line JSON summary on stdout. Uses the same shapes as the bench's
headline parts so the trace explains the bench line directly.
"""
from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _mesh():
    import numpy as np
    return Mesh(np.array(jax.devices()[:1]), ("tp",))


def _rand(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape,
                             jnp.float32).astype(jnp.bfloat16)


def make_ag_gemm(mesh):
    from triton_dist_tpu.ops.allgather_gemm import (
        create_ag_gemm_context, ag_gemm)
    m, k, n = 2048, 4096, 4096
    ctx = create_ag_gemm_context(mesh, "tp", interpret=False)
    a = jax.device_put(_rand(0, (m, k)), NamedSharding(mesh, P("tp")))
    b = jax.device_put(_rand(1, (k, n)),
                       NamedSharding(mesh, P(None, "tp")))
    return {impl: (lambda impl=impl: ag_gemm(a, b, ctx, impl=impl))
            for impl in ("pallas", "xla")}


def make_gemm_rs(mesh):
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        create_gemm_rs_context, gemm_rs)
    m, k, n = 2048, 4096, 4096
    ctx = create_gemm_rs_context(mesh, "tp", interpret=False)
    a = jax.device_put(_rand(0, (m, k)),
                       NamedSharding(mesh, P(None, "tp")))
    b = jax.device_put(_rand(1, (k, n)), NamedSharding(mesh, P("tp")))
    return {impl: (lambda impl=impl: gemm_rs(a, b, ctx, impl=impl))
            for impl in ("pallas", "xla")}


def make_tp_mlp(mesh):
    from triton_dist_tpu.layers.tp_mlp import TPMLP
    mlp = TPMLP(4096, 3072, mesh=mesh, axis="tp", dtype=jnp.bfloat16)
    params = mlp.init(jax.random.PRNGKey(0))
    x = jax.device_put(_rand(1, (2048, 4096)),
                       NamedSharding(mesh, P("tp")))
    return {"pallas": lambda: mlp(params, x, mode="ag_rs"),
            "xla": lambda: mlp(params, x, mode="xla")}


MAKERS = {"ag_gemm": make_ag_gemm, "gemm_rs": make_gemm_rs,
          "tp_mlp": make_tp_mlp}


def main() -> int:
    op = sys.argv[1] if len(sys.argv) > 1 else "ag_gemm"
    outdir = sys.argv[2] if len(sys.argv) > 2 else "profiles"
    fns = MAKERS[op](_mesh())
    summary = {"op": op}
    for impl, fn in fns.items():
        # Warm compile outside the trace.
        jax.block_until_ready(fn())
        path = os.path.join(outdir, f"{op}_{impl}")
        os.makedirs(path, exist_ok=True)
        with jax.profiler.trace(path):
            for _ in range(8):
                out = fn()
            jax.block_until_ready(out)
        summary[impl] = path
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
