"""Tunnel-recovery watcher: probe the axon backend at low cadence and,
the moment it answers, run the queued hardware evidence steps.

Why: the tunneled TPU backend wedges for 1-12 h at a time (see
BENCH_NOTES_r3.md); recovery windows are precious and must not be
missed. The watcher holds NO jax session itself — every probe and every
step is a fresh subprocess, and timed-out steps are ABANDONED, never
killed (SIGKILL mid-compile is the known wedge trigger).

Usage: nohup python scripts/hw_watch.py > hw_watch.out 2>&1 &
Writes progress to hw_watch.log; exits after the queue drains or a step
wedges the tunnel again (leaving the partial evidence on disk).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "hw_watch.log")

# (name, argv, deadline_s, env) — run in order; stop the queue if a
# step wedges (probe after each step to know).

QUEUE = [
    # Round-5 evidence queue, PERF-FIRST (VERDICT r4 next-1: "on any
    # tunnel window >=20 min, BENCH-quality numbers exist before
    # anything else runs"). Four rounds have produced zero
    # machine-captured TPU perf because smoke always ran first and the
    # window closed before the bench's turn.
    #
    # Position 1: the contract metrics alone — ag_gemm, gemm_rs,
    # gemm_ar, flash_decode, tp_mlp at the 2048x4096x4096 class.
    # ~10 min warm; up to ~32 min cold (the ag_gemm/gemm_rs autotune
    # sweeps are 7 Mosaic compiles each — budget sized so a cold sweep
    # is never mistaken for a wedge; on a shorter window the completed
    # parts still checkpoint incrementally). Dedicated checkpoint file
    # so a later wedged run can never erase it (bench.py's
    # probe-failure fallback scans all checkpoint paths; newest WITH
    # measured metrics wins, so an empty init checkpoint can't mask
    # this).
    ("bench_headline",
     [sys.executable, "bench.py"], 2100.0,
     {"TDT_BENCH_BUDGET_S": "1900",
      "TDT_BENCH_PARTS": "ag_gemm,gemm_rs,gemm_ar,flash_decode,tp_mlp",
      "TDT_BENCH_PROGRESS":
          os.path.join(ROOT, ".bench_progress_watcher_headline.json")}),
    # Position 2: the fused SP kernel's first-ever on-chip compile
    # (VERDICT r4 missing-2; three rounds export-lint-only).
    ("sp_pallas",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "600",
      "--only", "=sp_ag_attention/pallas",
      "--log", "tpu_smoke_r5_sp.log"],
     900.0, {}),
    # Position 3: the full 12-part bench (adds layer_8b/layer_32b
    # real-dim e2e, overlap, mega, moe, sp, train). Headline parts
    # recompile warm from position 1's cache.
    ("bench_full",
     [sys.executable, "bench.py"], 2700.0,
     {"TDT_BENCH_BUDGET_S": "2400",
      "TDT_BENCH_PROGRESS":
          os.path.join(ROOT, ".bench_progress_watcher.json")}),
    # Position 4: the train-step compile (observed 35 min once cold).
    ("train_step",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "900",
      "--only", "=train/fused_step",
      "--log", "tpu_smoke_r5_train.log"],
     1200.0, {}),
    # Positions 5-6: the smoke bulk, LAST (it is correctness evidence,
    # not the contract deliverable; ~2 h cold).
    ("smoke_bulk",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "420",
      "--skip", "train/fused_step,sp_ag_attention/pallas",
      "--log", "tpu_smoke_r5_bulk.log"],
     7200.0, {}),
    ("smoke_full",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "420",
      "--log", "tpu_smoke_r5.log"],
     7200.0, {}),
]


def commit_evidence() -> None:
    """Commit the evidence produced so far (host-side only — no tunnel
    contact, no probe gate). Runs after EVERY completed step so an
    unattended window leaves committed results even if a later step
    wedges the tunnel again (review r5h-1/2: a tail-of-queue commit
    step never runs in exactly that scenario, and retries appended
    behind it would produce evidence after the only commit)."""
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "scripts", "hw_evidence_commit.py")],
            capture_output=True, text=True, timeout=300.0, cwd=ROOT)
        tail = (r.stdout or r.stderr or "").strip().splitlines()
        log(f"evidence commit rc={r.returncode}"
            + (f" ({tail[-1][:100]})" if tail else ""))
    except (subprocess.TimeoutExpired, OSError) as e:
        log(f"evidence commit failed: {e!r}")


def log(msg: str) -> None:
    line = f"{time.strftime('%H:%M:%S')} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 60.0) -> bool:
    """Fresh-process jax.devices() probe. Killing a probe stuck in INIT
    (not compile) has been done dozens of times without consequence."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True, text=True, timeout=timeout_s, cwd=ROOT)
        return p.returncode == 0 and "TPU" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def run_step(name: str, argv: list[str], deadline_s: float,
             env_extra: dict | None = None) -> str:
    log(f"step {name}: start")
    env = dict(os.environ, **(env_extra or {}))
    # Keep every step's stdout (the bench's streamed cumulative JSON
    # lines are machine-captured evidence, not noise — review r4a-2).
    out = open(os.path.join(ROOT, f"hw_{name}.out"), "ab")
    child = subprocess.Popen(argv, cwd=ROOT, env=env,
                             stdout=out, stderr=subprocess.STDOUT)
    t0 = time.monotonic()
    try:
        while child.poll() is None:
            if time.monotonic() - t0 > deadline_s:
                log(f"step {name}: deadline {deadline_s:.0f}s — ABANDONED "
                    f"(pid {child.pid} left alive)")
                return "abandoned"
            time.sleep(10.0)
    finally:
        out.close()
    log(f"step {name}: done rc={child.returncode}")
    return "done"


def main() -> None:
    queue = list(QUEUE)
    retried: set[str] = set()
    log(f"watcher up, {len(queue)} steps queued")
    i = 0
    while i < len(queue):
        if not probe():
            log("tunnel wedged; sleeping 300s")
            time.sleep(300.0)
            continue
        log("tunnel ALIVE")
        name, argv, deadline, env_extra = queue[i]
        status = run_step(name, argv, deadline, env_extra)
        i += 1
        commit_evidence()
        if status == "abandoned":
            # The abandoned child may still own the (single) TPU client
            # slot — do NOT race it. But a later probe SUCCEEDING means
            # the backend answers again (the child finished or the
            # wedge cleared), so rather than ending the queue forever
            # (r3 behavior — it cost the whole evidence tail), wait for
            # health and continue; the abandoned step itself gets ONE
            # retry at the back of the queue (r4).
            log("step abandoned; waiting for the tunnel before the "
                "next step")
            if name not in retried:
                retried.add(name)
                queue.append((name, argv, deadline, env_extra))
                log(f"step {name}: re-queued once at the back")
            time.sleep(300.0)
    log("queue drained; watcher exiting")
    commit_evidence()
    with open(os.path.join(ROOT, ".hw_watch_done"), "w") as f:
        f.write(time.strftime("%Y-%m-%d %H:%M:%S") + "\n")


if __name__ == "__main__":
    main()
