"""Tunnel-recovery watcher: probe the axon backend at low cadence and,
the moment it answers, run the queued hardware evidence steps.

Why: the tunneled TPU backend wedges for 1-12 h at a time (see
BENCH_NOTES_r3.md); recovery windows are precious and must not be
missed. The watcher holds NO jax session itself — every probe and every
step is a fresh subprocess, and timed-out steps are ABANDONED, never
killed (SIGKILL mid-compile is the known wedge trigger).

Usage: nohup python scripts/hw_watch.py > hw_watch.out 2>&1 &
Writes progress to hw_watch.log; exits after the queue drains or a step
wedges the tunnel again (leaving the partial evidence on disk).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACTS = os.path.join(ROOT, "artifacts")
LOG = os.path.join(ARTIFACTS, "hw_watch.log")

# (name, argv, deadline_s, env) — run in order; stop the queue if a
# step wedges (probe after each step to know).

QUEUE = [
    # Round-5 SECOND queue (after first chip contact, 2026-08-01
    # morning: headline + full bench + train PASS captured; smoke
    # cases 1-27 PASS; run stopped at the flash_decode/paged compile
    # hang). Perf-first again; the wedge-risky paged case is LAST.
    #
    # Position 0: static-analysis preflight (docs/analysis.md) — pure
    # Python on the host, no tunnel contact. A ring-protocol or
    # VMEM-budget finding stops the whole queue before any step can
    # dial the chip with a schedule/config the checker rejects.
    # (tpu_smoke runs it again internally; this front-position copy
    # also guards the bench steps.)
    ("tdt_check_preflight",
     [sys.executable, "-m", "triton_dist_tpu.tools.tdt_check"],
     600.0, {"JAX_PLATFORMS": "cpu"}),
    #
    # Position 1: the parts the aborted full bench never reached
    # (sp_attn, train) plus the mega deep retry — all three now run
    # under the 64 MB scoped-VMEM limit that fixed the SP kernel's
    # 16.14 MB-vs-16 MB compile rejection.
    ("bench_gapfill",
     [sys.executable, "bench.py"], 2400.0,
     {"TDT_BENCH_BUDGET_S": "2100",
      "TDT_BENCH_PARTS": "sp_attn,mega,train",
      "TDT_BENCH_PROGRESS":
          os.path.join(ROOT, ".bench_progress_gapfill.json"),
      "TDT_DEVPROF_DIR": os.path.join(ARTIFACTS, "devprof_gapfill")}),
    # Post-bench device-profile validation (ISSUE 10): every capture
    # the bench step left must parse back through obs.devprof —
    # rc!=0 on an unparseable one, the same contract as the trace
    # validator. Host-side only, no tunnel contact. (The gapfill
    # parts carry no fused-family profile, so an empty dir passes;
    # the headline step's dir must hold them.)
    ("profile_validate_gapfill",
     [sys.executable, "-m", "triton_dist_tpu.tools.profile_export",
      "--validate", os.path.join(ARTIFACTS, "devprof_gapfill")],
     300.0, {"JAX_PLATFORMS": "cpu"}),
    # Position 2: headline re-run with the round-5 kernel changes
    # (24 MB default tile budget, large-tile sweep space, chained
    # sweep timing). Sweeps are now ~15 Mosaic compiles per GEMM op
    # (~8 min each cold) — budget sized for two cold sweeps; winners
    # disk-cache for the driver's end-of-round run.
    ("bench_headline2",
     [sys.executable, "bench.py"], 3300.0,
     {"TDT_BENCH_BUDGET_S": "3000",
      "TDT_BENCH_PARTS": "ag_gemm,gemm_rs,gemm_ar,flash_decode,tp_mlp",
      "TDT_BENCH_PROGRESS":
          os.path.join(ROOT, ".bench_progress_headline2.json"),
      "TDT_DEVPROF_DIR": os.path.join(ARTIFACTS, "devprof_headline2")}),
    # The headline step benches the fused family, so its devprof dir
    # MUST hold parseable captures (--require): measured overlap
    # evidence is the point of the next chip window (ROADMAP item 5).
    ("profile_validate_headline2",
     [sys.executable, "-m", "triton_dist_tpu.tools.profile_export",
      "--validate", "--require",
      os.path.join(ARTIFACTS, "devprof_headline2")],
     300.0, {"JAX_PLATFORMS": "cpu"}),
    # Position 3: the full smoke queue. The former flash_decode/paged
    # DIRECT-kernel canary — the round-5 wedge trigger the old queue
    # had to --start-after / --skip / quarantine at position 5 — is
    # retired from tpu_smoke.py entirely (ISSUE 6; docs/resilience.md
    # "Retired canary"), so the queue no longer needs a hang-point
    # partition: the production paged route is smoked as
    # flash_decode/paged_gathered like any other case.
    ("smoke_full",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "420",
      "--log", "artifacts/tpu_smoke_r6.log"],
     7200.0, {}),
]


def commit_evidence() -> None:
    """Commit the evidence produced so far (host-side only — no tunnel
    contact, no probe gate). Runs after EVERY completed step so an
    unattended window leaves committed results even if a later step
    wedges the tunnel again (review r5h-1/2: a tail-of-queue commit
    step never runs in exactly that scenario, and retries appended
    behind it would produce evidence after the only commit)."""
    try:
        r = subprocess.run(
            [sys.executable,
             os.path.join(ROOT, "scripts", "hw_evidence_commit.py")],
            capture_output=True, text=True, timeout=300.0, cwd=ROOT)
        tail = (r.stdout or r.stderr or "").strip().splitlines()
        log(f"evidence commit rc={r.returncode}"
            + (f" ({tail[-1][:100]})" if tail else ""))
    except (subprocess.TimeoutExpired, OSError) as e:
        log(f"evidence commit failed: {e!r}")


def log(msg: str) -> None:
    line = f"{time.strftime('%H:%M:%S')} {msg}"
    print(line, flush=True)
    os.makedirs(ARTIFACTS, exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 60.0) -> bool:
    """Fresh-process jax.devices() probe. Killing a probe stuck in INIT
    (not compile) has been done dozens of times without consequence."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True, text=True, timeout=timeout_s, cwd=ROOT)
        return p.returncode == 0 and "TPU" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def run_step(name: str, argv: list[str], deadline_s: float,
             env_extra: dict | None = None) -> str:
    log(f"step {name}: start")
    env = dict(os.environ, **(env_extra or {}))
    # Keep every step's stdout (the bench's streamed cumulative JSON
    # lines are machine-captured evidence, not noise — review r4a-2).
    os.makedirs(ARTIFACTS, exist_ok=True)
    out = open(os.path.join(ARTIFACTS, f"hw_{name}.out"), "ab")
    child = subprocess.Popen(argv, cwd=ROOT, env=env,
                             stdout=out, stderr=subprocess.STDOUT)
    t0 = time.monotonic()
    try:
        while child.poll() is None:
            if time.monotonic() - t0 > deadline_s:
                log(f"step {name}: deadline {deadline_s:.0f}s — ABANDONED "
                    f"(pid {child.pid} left alive)")
                return "abandoned"
            time.sleep(10.0)
    finally:
        out.close()
    log(f"step {name}: done rc={child.returncode}")
    return "done" if child.returncode == 0 else "failed"


def main() -> None:
    queue = list(QUEUE)
    retried: set[str] = set()
    log(f"watcher up, {len(queue)} steps queued")
    i = 0
    while i < len(queue):
        if not probe():
            log("tunnel wedged; sleeping 300s")
            time.sleep(300.0)
            continue
        log("tunnel ALIVE")
        name, argv, deadline, env_extra = queue[i]
        status = run_step(name, argv, deadline, env_extra)
        i += 1
        commit_evidence()
        if name == "tdt_check_preflight" and status == "failed":
            # The gate step: a static finding means later steps would
            # dial the chip with a schedule/config the checker rejects
            # — stop the whole queue (its log has the findings).
            log("preflight FAILED — queue stopped before any chip "
                "contact (see artifacts/hw_tdt_check_preflight.out)")
            return
        if status == "abandoned":
            # The abandoned child may still own the (single) TPU client
            # slot — do NOT race it. But a later probe SUCCEEDING means
            # the backend answers again (the child finished or the
            # wedge cleared), so rather than ending the queue forever
            # (r3 behavior — it cost the whole evidence tail), wait for
            # health and continue; the abandoned step itself gets ONE
            # retry at the back of the queue (r4).
            log("step abandoned; waiting for the tunnel before the "
                "next step")
            if name not in retried:
                retried.add(name)
                queue.append((name, argv, deadline, env_extra))
                log(f"step {name}: re-queued once at the back")
            time.sleep(300.0)
    log("queue drained; watcher exiting")
    commit_evidence()
    with open(os.path.join(ROOT, ".hw_watch_done"), "w") as f:
        f.write(time.strftime("%Y-%m-%d %H:%M:%S") + "\n")


if __name__ == "__main__":
    main()
