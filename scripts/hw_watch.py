"""Tunnel-recovery watcher: probe the axon backend at low cadence and,
the moment it answers, run the queued hardware evidence steps.

Why: the tunneled TPU backend wedges for 1-12 h at a time (see
BENCH_NOTES_r3.md); recovery windows are precious and must not be
missed. The watcher holds NO jax session itself — every probe and every
step is a fresh subprocess, and timed-out steps are ABANDONED, never
killed (SIGKILL mid-compile is the known wedge trigger).

Usage: nohup python scripts/hw_watch.py > hw_watch.out 2>&1 &
Writes progress to hw_watch.log; exits after the queue drains or a step
wedges the tunnel again (leaving the partial evidence on disk).
"""
from __future__ import annotations

import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "hw_watch.log")

# (name, argv, deadline_s, env) — run in order; stop the queue if a
# step wedges (probe after each step to know).


def _bench_part(part, deadline):
    return (f"bench_{part}", [sys.executable, "bench.py"], deadline,
            {"TDT_BENCH_ONLY": part, "TDT_BENCH_SUBPROC": "0",
             "TDT_BENCH_PROGRESS":
                 os.path.join(ROOT, f".bench_progress_{part}.json")})


QUEUE = [
    # Resume the stopped 07-31 03:30 smoke run: cases after
    # allreduce/one_shot (which PASSed; its lingering teardown falsely
    # stopped the old harness), minus the risky never-compiled ones.
    ("smoke_resume",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "420",
      "--start-after", "allreduce/one_shot",
      "--skip", "ag_gemm_multi,train/fused_step,sp_ag_attention/pallas",
      "--log", "tpu_smoke_r3_resume.log"],
     3600.0, {}),
    # First on-chip compile of the restructured fused SP kernel, alone
    # so a hang costs nothing else.
    ("sp_pallas",
     [sys.executable, "tpu_smoke.py", "--subproc", "--case-timeout", "600",
      "--only", "=sp_ag_attention/pallas",
      "--log", "tpu_smoke_r3_sp.log"],
     900.0, {}),
    # Re-measure the parts whose kernels changed since the 01:00 bench
    # (tp_mlp now routes ag_swiglu; mega/gemm_ar for fresh numbers).
    _bench_part("tp_mlp", 2700.0),
    _bench_part("moe_ag_gg", 2700.0),
    _bench_part("gemm_ar", 2700.0),
    _bench_part("mega", 2700.0),
    # The grouped SP kernel and the persistent compile cache give these
    # two a real shot now; run them LAST so a long compile only costs
    # the tail. A once-successful train compile persists in .jax_cache,
    # making the driver's end-of-round bench near-free.
    _bench_part("sp_attn", 2700.0),
    _bench_part("train", 5400.0),
]


def log(msg: str) -> None:
    line = f"{time.strftime('%H:%M:%S')} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 60.0) -> bool:
    """Fresh-process jax.devices() probe. Killing a probe stuck in INIT
    (not compile) has been done dozens of times without consequence."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True, text=True, timeout=timeout_s, cwd=ROOT)
        return p.returncode == 0 and "TPU" in p.stdout
    except subprocess.TimeoutExpired:
        return False


def run_step(name: str, argv: list[str], deadline_s: float,
             env_extra: dict | None = None) -> str:
    log(f"step {name}: start")
    env = dict(os.environ, **(env_extra or {}))
    child = subprocess.Popen(argv, cwd=ROOT, env=env,
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL)
    t0 = time.monotonic()
    while child.poll() is None:
        if time.monotonic() - t0 > deadline_s:
            log(f"step {name}: deadline {deadline_s:.0f}s — ABANDONED "
                f"(pid {child.pid} left alive)")
            return "abandoned"
        time.sleep(10.0)
    log(f"step {name}: done rc={child.returncode}")
    return "done"


def main() -> None:
    log(f"watcher up, {len(QUEUE)} steps queued")
    i = 0
    while i < len(QUEUE):
        if not probe():
            log("tunnel wedged; sleeping 300s")
            time.sleep(300.0)
            continue
        log("tunnel ALIVE")
        name, argv, deadline, env_extra = QUEUE[i]
        status = run_step(name, argv, deadline, env_extra)
        i += 1
        if status == "abandoned":
            # The abandoned child is still alive and owns the (single)
            # TPU client slot; starting another step would contend for
            # the backend and can wedge the tunnel harder. Stop here —
            # partial evidence is on disk.
            log("step abandoned; stopping the queue (abandoned child "
                "still holds the backend)")
            break
    log("queue drained; watcher exiting")
    with open(os.path.join(ROOT, ".hw_watch_done"), "w") as f:
        f.write(time.strftime("%Y-%m-%d %H:%M:%S") + "\n")


if __name__ == "__main__":
    main()
