#!/usr/bin/env bash
# Multi-host launcher (the reference's scripts/launch.sh torchrun wrapper,
# re-shaped for JAX multi-process: one process per host, coordinator env
# instead of torchrun rendezvous).
#
# Usage (run the SAME command on every host):
#   COORDINATOR=host0:8476 NPROC=4 PROC_ID=<this host idx> \
#       scripts/launch.sh python tests/... | examples/... | bench.py
#
# On Cloud TPU pods the launcher env is usually injected already
# (JAX_COORDINATOR_ADDRESS etc.) — then just `python your_script.py`;
# this wrapper is for manual bring-up and matches the reference's
# env-plumbing role (NVSHMEM_*/NCCL_* ≙ JAX_*/TPU_* here).
set -euo pipefail

if [[ $# -lt 1 ]]; then
  echo "usage: COORDINATOR=host:port NPROC=n PROC_ID=i $0 <cmd...>" >&2
  exit 2
fi

# Coordinator plumbing (reference launch.sh reads ARNOLD_*/RANK env).
export JAX_COORDINATOR_ADDRESS="${COORDINATOR:-${JAX_COORDINATOR_ADDRESS:-}}"
export JAX_NUM_PROCESSES="${NPROC:-${JAX_NUM_PROCESSES:-1}}"
export JAX_PROCESS_ID="${PROC_ID:-${JAX_PROCESS_ID:-0}}"

# Sane defaults mirroring the reference's forced env
# (CUDA_DEVICE_MAX_CONNECTIONS=1, NVSHMEM_SYMMETRIC_SIZE):
#  - keep compilation cache on (first Mosaic compile is slow)
#  - un-filtered tracebacks for actionable crash reports
export JAX_TRACEBACK_FILTERING="${JAX_TRACEBACK_FILTERING:-off}"
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$HOME/.cache/jax_comp}"
export TDT_AUTOTUNE_CACHE="${TDT_AUTOTUNE_CACHE:-1}"

if [[ -n "${JAX_COORDINATOR_ADDRESS}" ]]; then
  echo "[launch] proc ${JAX_PROCESS_ID}/${JAX_NUM_PROCESSES}" \
       "coordinator ${JAX_COORDINATOR_ADDRESS}" >&2
else
  echo "[launch] single-host (no COORDINATOR set)" >&2
fi

exec "$@"
