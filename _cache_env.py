"""Shared pre-jax-import env for the persistent XLA compilation cache.

Import this BEFORE jax in every repo-root entry point that touches the
tunneled TPU (bench.py, tpu_smoke.py): a once-successful compile of the
big fused programs (the train step was observed >35 min through the
tunnel) then persists to .jax_cache, making later runs — including the
driver's end-of-round bench — near-free. One module so the two entry
points cannot drift (code-review r3f finding 1). Harmless if the
backend declines executable serialization.
"""

import os

os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "5")
